"""paddle.summary (parity: python/paddle/hapi/model_summary.py).

Runs one forward pass with layer hooks to collect per-layer output shapes
and parameter counts, printing the reference-style table.
"""
from __future__ import annotations

import numbers
from collections import OrderedDict

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["summary", "summary_string"]


def _to_input_spec_shapes(input_size):
    """Normalize input_size into a list of shape tuples."""
    from ..jit.api import InputSpec
    if isinstance(input_size, InputSpec):
        return [tuple(input_size.shape)], [getattr(input_size, "dtype", None)]
    if isinstance(input_size, tuple) and all(
            isinstance(d, numbers.Number) for d in input_size):
        return [tuple(input_size)], [None]
    shapes, dtypes = [], []
    for item in input_size:
        s, d = _to_input_spec_shapes(item)
        shapes += s
        dtypes += d
    return shapes, dtypes


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer summary table; returns {'total_params', 'trainable_params'}."""
    text, params_info = summary_string(net, input_size, dtypes, input)
    print(text)
    return params_info


def summary_string(net, input_size=None, dtypes=None, input=None):
    if input is None and input_size is None:
        raise ValueError("input_size and input cannot both be None")
    if input is None:
        shapes, spec_dtypes = _to_input_spec_shapes(input_size)
        if dtypes is None:
            dtypes = [d or "float32" for d in spec_dtypes]
        elif isinstance(dtypes, str):
            dtypes = [dtypes] * len(shapes)
        inputs = []
        for shape, dt in zip(shapes, dtypes):
            shape = tuple(1 if (d is None or d < 0) else d for d in shape)
            if "int" in str(dt):
                inputs.append(Tensor(np.zeros(shape, dtype=str(dt))))
            else:
                inputs.append(Tensor(np.random.rand(*shape).astype(str(dt))))
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    layer_info = OrderedDict()
    hooks = []

    def register(module, prefix=""):
        for name, sub in module.named_children():
            full = prefix + ("." if prefix else "") + name
            if not list(sub.named_children()):
                hooks.append((full, sub))
            register(sub, full)
        if prefix == "" and not hooks:
            hooks.append((module.__class__.__name__, module))

    register(net)

    handles = []

    def make_hook(key, layer):
        def hook(l, inp, out):
            info = {}
            o = out[0] if isinstance(out, (list, tuple)) and out else out
            try:
                info["output_shape"] = list(o.shape)
            except Exception:
                info["output_shape"] = []
            n_params = 0
            n_train = 0
            for p in layer.parameters(include_sublayers=False):
                n = int(np.prod(p.shape)) if p.shape else 1
                n_params += n
                if not p.stop_gradient:
                    n_train += n
            info["nb_params"] = n_params
            info["trainable_params"] = n_train
            layer_info["%s (%s)" % (key, layer.__class__.__name__)] = info
        return hook

    for key, layer in hooks:
        handles.append(layer.register_forward_post_hook(make_hook(key, layer)))

    was_training = getattr(net, "training", True)
    net.eval()
    try:
        net(*inputs)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    header = "{:<40} {:>22} {:>15}".format("Layer (type)", "Output Shape",
                                           "Param #")
    lines = ["-" * 79, header, "=" * 79]
    total_params = 0
    trainable_params = 0
    for key, info in layer_info.items():
        total_params += info["nb_params"]
        trainable_params += info["trainable_params"]
        lines.append("{:<40} {:>22} {:>15,}".format(
            key[:40], str(info["output_shape"])[:22], info["nb_params"]))
    # include parameters held directly by container layers not hooked
    seen = 0
    for p in net.parameters():
        seen += int(np.prod(p.shape)) if p.shape else 1
    if seen > total_params:   # some params (e.g. on container) missed by hooks
        total_params = seen
        trainable_params = sum(
            (int(np.prod(p.shape)) if p.shape else 1)
            for p in net.parameters() if not p.stop_gradient)
    lines.append("=" * 79)
    lines.append("Total params: {:,}".format(total_params))
    lines.append("Trainable params: {:,}".format(trainable_params))
    lines.append("Non-trainable params: {:,}".format(
        total_params - trainable_params))
    lines.append("-" * 79)
    return "\n".join(lines), {"total_params": total_params,
                              "trainable_params": trainable_params}
