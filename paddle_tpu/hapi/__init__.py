"""hapi — high-level training API (parity: python/paddle/hapi/)."""
from . import callbacks
from .model import Model
from .model_summary import summary

__all__ = ["Model", "summary", "callbacks"]
