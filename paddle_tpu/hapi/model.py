"""hapi Model — Keras-like high-level trainer.

Parity: python/paddle/hapi/model.py (reference — Model :1054, fit :1756,
evaluate, predict, save/load, train_batch/eval_batch/predict_batch).

TPU-native notes: the train loop is eager-tape by default (flexible for any
loss/metric combination); `prepare(..., jit=True)` (an extension) swaps the
per-batch path for a fully-fused XLA TrainStep (forward+backward+update in
one donated-buffer module) when the loss takes (output, label).
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..metric import Metric
from .callbacks import config_callbacks

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _to_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


class Model:
    """High-level API wrapping a Layer for training/eval/inference
    (parity: paddle.Model)."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._scaler = None
        self._amp_level = "O0"
        self._jit_step = None
        self._use_jit = False
        self.stop_training = False
        self.save_dir = None

    # -- prepare -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=False):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer)
                                     or callable(loss)):
            raise TypeError(
                "'loss' must be sub classes of `paddle.nn.Layer` or any "
                "callable function.")
        self._loss = loss
        metrics = metrics or []
        for m in _to_list(metrics):
            if not isinstance(m, Metric):
                raise TypeError(
                    "{} is not sub class of Metric".format(
                        m.__class__.__name__))
        self._metrics = _to_list(metrics)
        self._use_jit = bool(jit)
        if amp_configs is not None:
            from .. import amp as amp_mod
            if isinstance(amp_configs, str):
                amp_configs = {"level": amp_configs}
            level = amp_configs.get("level", "O1")
            self._amp_level = level
            if level != "O0":
                scaler_kw = {k: v for k, v in amp_configs.items()
                             if k not in ("level", "dtype")}
                self._scaler = amp_mod.GradScaler(**scaler_kw)

    # -- single-batch APIs ----------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        assert self._optimizer is not None, (
            "model not ready, please call `model.prepare()` first")
        self.network.train()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]

        if self._use_jit and self._loss is not None and len(labels) == 1:
            if self._jit_step is None:
                from ..jit.train_step import TrainStep
                self._jit_step = TrainStep(self.network, self._loss,
                                           self._optimizer)
                if self._metrics:
                    warnings.warn(
                        "prepare(jit=True) fuses forward+backward+update "
                        "into one XLA call and does not re-expose model "
                        "outputs; metrics are skipped during fit. Use "
                        "evaluate() for metrics.")
            loss = self._jit_step(*[t._value for t in inputs],
                                  labels[0]._value)
            return self._pack_losses(float(np.asarray(loss)))

        from .. import amp as amp_mod
        if self._amp_level != "O0":
            ctx = amp_mod.auto_cast(enable=True, level=self._amp_level)
        else:
            import contextlib
            ctx = contextlib.nullcontext()
        with ctx:
            outputs = self.network(*inputs)
            losses = self._compute_loss(outputs, labels)
        total = losses[0]
        for extra in losses[1:]:
            total = total + extra
        if self._scaler is not None:
            self._scaler.scale(total).backward()
            if update:
                self._scaler.step(self._optimizer)
                self._scaler.update()
                self._optimizer.clear_grad()
        else:
            total.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = self._run_metrics(outputs, labels)
        return self._pack_losses(
            [float(np.asarray(l._value)) for l in losses]) + metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        labels = [_to_tensor(x) for x in _to_list(labels)]
        from ..autograd.tape import no_grad
        with no_grad():
            outputs = self.network(*inputs)
            losses = (self._compute_loss(outputs, labels)
                      if self._loss is not None else [])
        metrics = self._run_metrics(outputs, labels)
        # slot layout must mirror _run_eval's metric_names: a loss slot
        # exists only when a loss fn is prepared
        if self._loss is None:
            return metrics
        loss_vals = [float(np.asarray(l._value)) for l in losses]
        return self._pack_losses(loss_vals) + metrics

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = [_to_tensor(x) for x in _to_list(inputs)]
        from ..autograd.tape import no_grad
        with no_grad():
            outputs = self.network(*inputs)
        outs = _to_list(outputs)
        return [np.asarray(o._value) for o in outs]

    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        if self._loss is None:
            raise RuntimeError("loss is required; pass it to prepare()")
        try:
            loss = self._loss(*(outs + labels))
        except TypeError:
            loss = self._loss(outs[0], labels[0])
        return _to_list(loss)

    def _run_metrics(self, outputs, labels):
        vals = []
        outs = _to_list(outputs) if outputs is not None else []
        for metric in self._metrics:
            if outs:
                res = metric.compute(*(outs + labels))
                m = metric.update(*[np.asarray(r._value)
                                    if isinstance(r, Tensor) else r
                                    for r in _to_list(res)])
                vals.append(m)
        return vals

    @staticmethod
    def _pack_losses(losses):
        """Wrap into the reference's [loss_list, metric...] slot layout."""
        return [losses if isinstance(losses, list) else [losses]]

    # -- loops ---------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io import DataLoader, Dataset, IterableDataset
        if isinstance(data, DataLoader):
            return data
        if hasattr(data, "__iter__") and not isinstance(
                data, (Dataset, IterableDataset)):
            # a one-shot iterator would silently yield nothing from epoch 2
            # on — materialize it; re-iterable containers pass through
            if hasattr(data, "__next__"):
                return list(data)
            return data   # list of batches
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    def _split_batch(self, batch):
        """Split a collated batch into (inputs, labels) using declared specs
        or a trailing-label convention."""
        batch = _to_list(batch)
        n_in = len(self._inputs) if self._inputs else None
        if n_in:
            return batch[:n_in], batch[n_in:]
        if len(batch) == 1:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        assert train_data is not None, "train_data must be given!"
        self.save_dir = save_dir
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = (self._make_loader(eval_data, batch_size, False,
                                         num_workers, False)
                       if eval_data is not None else None)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        metric_names = ["loss"] + [m.name() for m in self._metrics]
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                batch_size=batch_size, steps=steps,
                                log_freq=log_freq, save_freq=save_freq,
                                save_dir=save_dir, verbose=verbose,
                                metrics=metric_names)
        self.stop_training = False
        cbks.on_begin("train")
        total_iters = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            logs = {}
            step = 0
            for batch in loader:
                cbks.on_batch_begin("train", step, logs)
                inputs, labels = self._split_batch(batch)
                update = ((step + 1) % accumulate_grad_batches == 0)
                outs = self.train_batch(inputs, labels, update=update)
                logs = self._make_logs(outs, metric_names)
                logs["batch_size"] = (inputs[0].shape[0]
                                      if inputs and inputs[0].shape else
                                      batch_size)
                cbks.on_batch_end("train", step, logs)
                step += 1
                total_iters += 1
                if num_iters is not None and total_iters >= num_iters:
                    self.stop_training = True
                    break
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch % eval_freq) == 0:
                eval_logs = self._run_eval(eval_loader, cbks, log_freq)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
        cbks.on_end("train", logs)
        return logs

    def _make_logs(self, outs, metric_names):
        logs = {}
        i = 0
        for name in metric_names:
            if i >= len(outs):
                break
            v = outs[i]
            if isinstance(v, list):
                v = v[0] if v else 0.0
            logs[name] = v
            i += 1
        return logs

    def _run_eval(self, loader, outer_cbks, log_freq):
        for m in self._metrics:
            m.reset()
        metric_names = (["loss"] if self._loss else []) + [
            m.name() for m in self._metrics]
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        outer_cbks.on_begin("eval",
                            {"steps": steps, "metrics": metric_names})
        logs = {}
        count = 0
        for step, batch in enumerate(loader):
            outer_cbks.on_batch_begin("eval", step, logs)
            inputs, labels = self._split_batch(batch)
            outs = self.eval_batch(inputs, labels)
            logs = self._make_logs(outs, metric_names)
            count += (inputs[0].shape[0] if inputs and inputs[0].shape else 1)
            logs["batch_size"] = count
            outer_cbks.on_batch_end("eval", step, logs)
        outer_cbks.on_end("eval", logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._make_loader(eval_data, batch_size, False,
                                   num_workers, False)
        metric_names = (["loss"] if self._loss else []) + [
            m.name() for m in self._metrics]
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                log_freq=log_freq, verbose=verbose,
                                metrics=metric_names, mode="eval")
        return self._run_eval(loader, cbks, log_freq)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False,
                                   num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, batch_size=batch_size,
                                verbose=verbose, metrics=[], mode="test")
        cbks.on_begin("predict", {"steps": steps})
        outputs = []
        count = 0
        for step, batch in enumerate(loader):
            inputs, _ = self._split_batch(batch)
            cbks.on_batch_begin("predict", step, {})
            outs = self.predict_batch(inputs)
            outputs.append(outs)
            count += (inputs[0].shape[0] if inputs and inputs[0].shape else 1)
            cbks.on_batch_end("predict", step, {"batch_size": count})
        # transpose: list over batches of list over outputs -> list over outputs
        n_out = len(outputs[0]) if outputs else 0
        result = [[batch[i] for batch in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        cbks.on_end("predict", {"batch_size": count})
        return result

    # -- persistence ----------------------------------------------------------
    def save(self, path, training=True):
        """training=True saves .pdparams/.pdopt; False exports for inference
        via jit.save (requires declared input specs)."""
        if not training:
            from .. import jit as jit_mod
            if not self._inputs:
                raise ValueError(
                    "'inputs' must be declared on Model(...) for inference "
                    "export")
            jit_mod.save(self.network, path, input_spec=self._inputs)
            return
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        from .. import framework_io
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework_io
        param_path = path + ".pdparams" if not path.endswith(".pdparams") \
            else path
        state = framework_io.load(param_path)
        if skip_mismatch:
            own = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in own and tuple(np.asarray(v).shape) ==
                     tuple(own[k].shape)}
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if (not reset_optimizer and self._optimizer is not None
                and os.path.exists(opt_path)):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        if input_size is None:
            if not self._inputs:
                raise ValueError("input_size or declared inputs required")
            input_size = self._inputs
        return summary(self.network, input_size, dtypes=dtype)
