"""Terminal progress bar for hapi (parity: python/paddle/hapi/progressbar.py).

Kept dependency-free: renders `step/total - metric: value` lines with a
simple bar when the total is known, dots otherwise.
"""
from __future__ import annotations

import sys
import time

import numpy as np


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._values = {}
        self._values_order = []
        self._start = time.time() if start else None
        self._last_update = 0

    def _get_max_width(self):
        return 80

    def start(self):
        self.file.flush()
        self._start = time.time()

    def update(self, current_num, values=None):
        now = time.time()
        if values:
            for name, val in values:
                if name not in self._values_order:
                    self._values_order.append(name)
                self._values[name] = val

        if self._verbose == 0:
            return

        info = ""
        if self._num is not None:
            numdigits = len(str(self._num))
            bar_chars = ("step %" + str(numdigits) + "d/%d") % (
                current_num, self._num)
        else:
            bar_chars = "step %d" % current_num

        for name in self._values_order:
            val = self._values[name]
            info += " - %s:" % name
            val = val if isinstance(val, (list, tuple)) else [val]
            for v in val:
                if isinstance(v, (float, np.float32, np.float64)):
                    if abs(v) > 1e-3:
                        info += " %.4f" % v
                    else:
                        info += " %.4e" % v
                else:
                    info += " %s" % v

        elapsed = now - self._start if self._start else 0
        if current_num:
            info += " - %.0fms/step" % (elapsed / current_num * 1000)

        if self._verbose == 1:
            self.file.write("\r" + bar_chars + info)
            if self._num is not None and current_num >= self._num:
                self.file.write("\n")
        else:
            self.file.write(bar_chars + info + "\n")
        self.file.flush()
        self._last_update = now
