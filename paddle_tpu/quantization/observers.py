"""Parity import path: paddle.quantization.observers (__all__ =
[AbsmaxObserver]); implementation in the package __init__."""
from . import AbsmaxObserver

__all__ = ["AbsmaxObserver"]
