"""Parity import path: paddle.quantization.quanters (__all__ =
[FakeQuanterWithAbsMaxObserver]); implementation in the package
__init__."""
from . import FakeQuanterWithAbsMaxObserver

__all__ = ["FakeQuanterWithAbsMaxObserver"]
