"""Symmetric-absmax quantization primitives — the ONE implementation.

Every quantizer in the repo routes through these four functions: the
QAT fake-quant path (``paddle_tpu.quantization._fake_quant``, straight-
through estimator around :func:`fake_quantize`), the serving
post-training weight quantizer (:func:`quantize_param_tree`, consumed
by the fused serving steps via dequant-on-use), and the int8 paged KV
cache (``ops/paged_attention``'s quantized write paths).  One clamp
convention everywhere: symmetric around zero, ``bnt = 2**(bits-1) - 1``
levels per side (so int8 uses [-127, 127]; -128 is never produced and a
negated tensor quantizes to the negated codes), round-half-even
(``jnp.round``), and a floor on the scale so a zero tensor quantizes to
zeros instead of NaN.

``scale`` is always the ABSMAX of the data being quantized (codes are
``x / scale * bnt``), never the per-level step — matching the
convention of ``AbsmaxObserver`` / the channel-wise observers in
``quantization/``.

This module imports only jax/numpy (no ``paddle_tpu.nn``), so the ops
and jit layers can use it without pulling the full quantization API;
the heavy layer-wrapping machinery stays in ``quantization/__init__``.
"""
from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["symmetric_bound", "absmax_scale", "quantize_symmetric",
           "dequantize_symmetric", "fake_quantize",
           "quantize_rows_symmetric", "fold_int8_scores",
           "WEIGHT_SCALE_SUFFIX", "is_weight_scale_key",
           "ptq_quantizable", "quantize_param_tree",
           "dequantize_param_tree"]

# the serving PTQ tree stores each quantized weight's per-channel absmax
# next to it under this suffixed key ("<param>::scale"); jit/spmd.py
# classifies these keys into 1-D PartitionSpecs for tensor parallelism
WEIGHT_SCALE_SUFFIX = "::scale"

# weight families eligible for serving PTQ: the 2-D projection matmuls.
# Embeddings stay fp (the lookup is memory-bound, not matmul-bound, and
# a tied lm_head must keep the fp table the untied path samples from);
# norms/biases are 1-D and replicated.
_PTQ_FAMILIES = ("q_proj", "k_proj", "v_proj", "o_proj", "gate_proj",
                 "up_proj", "down_proj", "lm_head")

# 3-D batched MoE expert banks ([E, D, M] / [E, M, D]); quantized
# per-expert-per-output-channel (absmax over the contraction dim).  The
# router ("...block_sparse_moe.gate.weight") stays fp — routing logits
# are tiny and drive a top-k whose ties must match the eager reference.
_PTQ_EXPERT_FAMILIES = ("w_gate", "w_up", "w_down")


def symmetric_bound(bits: int = 8) -> int:
    """Largest code magnitude: 127 for int8."""
    return (1 << (int(bits) - 1)) - 1


def absmax_scale(x, axis=None, keepdims: bool = False):
    """Absmax over ``axis`` (None = whole tensor) in fp32 — the
    symmetric scale.  No epsilon here; the quant/dequant pair floors
    the scale itself so absmax stays exact for observers."""
    return jnp.max(jnp.abs(jnp.asarray(x).astype(jnp.float32)),
                   axis=axis, keepdims=keepdims)


def quantize_symmetric(x, scale, bits: int = 8):
    """Codes in [-bnt, bnt] (float dtype — cast at the storage site).

    ``scale`` is the absmax and must broadcast against ``x``."""
    bnt = symmetric_bound(bits)
    s = jnp.maximum(jnp.asarray(scale).astype(jnp.float32), 1e-30)
    return jnp.clip(jnp.round(x.astype(jnp.float32) / s * bnt),
                    -bnt, bnt)


def dequantize_symmetric(q, scale, bits: int = 8):
    """Codes (+ their absmax scale) back to fp32 values."""
    bnt = symmetric_bound(bits)
    return (q.astype(jnp.float32)
            * (jnp.asarray(scale).astype(jnp.float32) / bnt))


def quantize_rows_symmetric(x, bits: int = 8):
    """Per-ROW symmetric int8 codes + their absmax scales — the
    in-kernel MXU-operand quantizer (round 17).

    ``x``: [rows, d] fp values (one attention-kernel q row per query
    head × span position).  Returns ``(codes int8 [rows, d],
    scale f32 [rows, 1])`` with the same clamp convention as
    :func:`quantize_symmetric` (codes in [-bnt, bnt], scale floored so
    an all-zero row — a padded span tail — quantizes to zeros, never
    NaN).  Traceable inside Pallas kernel bodies: jnp ops only, and the
    int8 cast happens here so the caller can feed the codes straight
    into an int8×int8 ``dot_general``."""
    bnt = symmetric_bound(bits)
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    s = jnp.maximum(scale, 1e-30)
    codes = jnp.clip(jnp.round(xf / s * bnt), -bnt, bnt).astype(jnp.int8)
    return codes, s


def fold_int8_scores(acc, q_scale, k_scale, softmax_scale=1.0,
                     bits: int = 8):
    """Fold the two absmax scales (and the softmax 1/sqrt(D)) into an
    int8×int8 matmul's int32-accumulated scores — the round-17
    replacement for dequantizing whole KV pages into fp32 VMEM.

    ``acc``: [rows, cols] int32 accumulator of ``q_codes · k_codesᵀ``;
    ``q_scale``: [rows, 1] per-row q absmax (from
    :func:`quantize_rows_symmetric`); ``k_scale``: the page's scalar
    per-page-per-head absmax.  Exact identity being approximated:
    ``(q/qs·bnt)·(k/ks·bnt)ᵀ · qs·ks/bnt² ≈ q·kᵀ`` — the only error is
    the two quantizations, never the fold (scalar multiplies commute
    with the dot).  Returns fp32 scores ready for the online softmax."""
    bnt = symmetric_bound(bits)
    mult = q_scale.astype(jnp.float32) * (
        jnp.asarray(k_scale, jnp.float32)
        * np.float32(float(softmax_scale) / (bnt * bnt)))
    return acc.astype(jnp.float32) * mult


def fake_quantize(x, scale, bits: int = 8):
    """quantize→dequantize round trip (QAT forward math; wrap with a
    straight-through estimator for the gradient)."""
    return dequantize_symmetric(quantize_symmetric(x, scale, bits),
                                scale, bits)


# ---------------------------------------------------------------------------
# serving PTQ: per-channel int8 weight tree
# ---------------------------------------------------------------------------
def is_weight_scale_key(key: str) -> bool:
    return key.endswith(WEIGHT_SCALE_SUFFIX)


def ptq_quantizable(key: str, value) -> bool:
    """2-D projection weights (``_PTQ_FAMILIES``) and 3-D batched expert
    banks (``_PTQ_EXPERT_FAMILIES``)."""
    if is_weight_scale_key(key):
        return False
    if key.endswith("weight") and getattr(value, "ndim", 0) == 2:
        return any(f in key for f in _PTQ_FAMILIES)
    if getattr(value, "ndim", 0) == 3:
        return any(key.endswith(f) for f in _PTQ_EXPERT_FAMILIES)
    return False


def quantize_param_tree(values: Dict[str, jnp.ndarray],
                        bits: int = 8) -> Dict[str, jnp.ndarray]:
    """Per-output-channel absmax PTQ over a serving state dict.

    Linear weights are ``[in, out]``; each output channel gets its own
    absmax scale (axis-0 reduction → ``[out]`` fp32 vector stored at
    ``key + WEIGHT_SCALE_SUFFIX``), and the weight itself is replaced
    by its int8 codes.  Everything else (embeddings, norms, biases)
    passes through untouched, so the tree keeps every key the model's
    ``bind_state`` expects plus the scale vectors the steps dequantize
    with.
    """
    out: Dict[str, jnp.ndarray] = {}
    for k, v in values.items():
        v = jnp.asarray(v)
        if not ptq_quantizable(k, v):
            out[k] = v
            continue
        if v.ndim == 3:
            # expert bank [E, in, out]: absmax over the contraction dim
            # -> per-expert-per-output-channel scale [E, 1, out], stored
            # full-rank so the spec layer can shard its E dim with P(ep)
            scale = absmax_scale(v, axis=1, keepdims=True)
            q = quantize_symmetric(v, scale, bits).astype(jnp.int8)
            out[k] = q
            out[k + WEIGHT_SCALE_SUFFIX] = scale           # [E, 1, out]
            continue
        scale = absmax_scale(v, axis=0, keepdims=True)     # [1, out]
        q = quantize_symmetric(v, scale, bits).astype(jnp.int8)
        out[k] = q
        out[k + WEIGHT_SCALE_SUFFIX] = scale[0]            # [out]
    return out


def dequantize_param_tree(params: Dict[str, jnp.ndarray], dtype,
                          bits: int = 8) -> Dict[str, jnp.ndarray]:
    """Traceable dequant-on-use prologue for the fused serving steps:
    int8 weights × their scale vectors back to ``dtype``, scale keys
    dropped, everything else passed through.  Composed INSIDE the
    compiled step, so HBM holds the int8 tree and XLA fuses the
    dequant into the consuming matmuls."""
    out: Dict[str, jnp.ndarray] = {}
    for k, v in params.items():
        if is_weight_scale_key(k):
            continue
        s = params.get(k + WEIGHT_SCALE_SUFFIX)
        if s is None:
            out[k] = v
        else:
            # 1-D scales broadcast against [in, out]; full-rank scales
            # (expert banks [E, 1, out]) broadcast as stored
            if getattr(s, "ndim", 1) == 1:
                s = s[None, :]
            out[k] = dequantize_symmetric(v, s, bits).astype(dtype)
    return out
