"""paddle.quantization — QAT/PTQ.

Parity: python/paddle/quantization/ (reference — QuantConfig config.py:60,
QuanterFactory factory.py, observers/, quanters/, QAT qat.py, PTQ ptq.py,
quanter/observer wrapping in wrapper.py).

TPU-native: fake-quant is a pure function with a straight-through
estimator (x + stop_gradient(q(x) - x)), so the quantized graph traces
and fuses under XLA like any other op; int8 simulation stays in the
compiled module.
"""
from __future__ import annotations

import copy
from typing import Dict, List, Optional, Type, Union

import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..nn.layer_base import Layer
from .. import nn
from .functional import fake_quantize

__all__ = ["QuantConfig", "SingleLayerConfig", "QuanterFactory",
           "BaseObserver", "BaseQuanter", "AbsmaxObserver",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterChannelWiseAbsMaxObserver", "QAT", "PTQ",
           "QuantedLinear", "QuantedConv2D", "quanter"]


def _fake_quant(x, scale, bit_length=8):
    """Symmetric fake quantization with STE gradient.

    The forward math is ``quantization.functional.fake_quantize`` — the
    SAME symmetric-absmax clamp (round-half-even into [-bnt, bnt]) the
    serving PTQ path (``quantize_param_tree``) and the int8 KV cache
    use, so QAT training simulates exactly what deployment runs."""

    def fn(v, s):
        q = fake_quantize(v, s, bit_length).astype(v.dtype)
        # straight-through estimator: identity gradient w.r.t. v
        return v + jax.lax.stop_gradient(q - v)

    return apply_op("fake_quant", fn, (x, _targ(scale)))


def _targ(x):
    return x if isinstance(x, Tensor) else Tensor(np.asarray(x, np.float32))


# ---------------------------------------------------------------------------
# observers & quanters
# ---------------------------------------------------------------------------
class BaseObserver(Layer):
    """Parity: base_observer.py — collects statistics, provides scales."""

    def __init__(self):
        super().__init__()

    def scales(self):
        raise NotImplementedError

    def bit_length(self):
        return 8

    def quant_axis(self):
        return -1


class BaseQuanter(BaseObserver):
    """Parity: base_quanter.py."""


class AbsmaxObserver(BaseObserver):
    """PTQ observer: running abs-max (parity: observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self._max = 1e-9

    def forward(self, x):
        self._max = max(self._max,
                        float(np.max(np.abs(np.asarray(x._value)))))
        return x

    def scales(self):
        return Tensor(np.asarray(self._max, np.float32))

    def bit_length(self):
        return self._quant_bits


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """QAT quanter: moving-average abs-max + fake quant with STE
    (parity: quanters/abs_max.py FakeQuanterWithAbsMaxObserverLayer)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        self._state = 0.0
        self._accum = 0.0
        self._scale = 1e-9

    def forward(self, x):
        if self.training:
            cur = float(np.max(np.abs(np.asarray(x._value)))) + 1e-9
            r = self._moving_rate
            self._state = r * self._state + 1.0
            self._accum = r * self._accum + cur
            self._scale = self._accum / self._state
        return _fake_quant(x, self._scale, self._bit_length)

    def scales(self):
        return Tensor(np.asarray(self._scale, np.float32))

    def bit_length(self):
        return self._bit_length


class FakeQuanterChannelWiseAbsMaxObserver(BaseQuanter):
    """Per-output-channel weight quanter (parity:
    quanters/abs_max.py channel-wise variant)."""

    def __init__(self, quant_axis=0, bit_length=8, **kw):
        super().__init__()
        self._axis = quant_axis
        self._bit_length = bit_length
        self._scale = None

    def forward(self, w):
        arr = np.asarray(w._value)
        axes = tuple(i for i in range(arr.ndim) if i != self._axis)
        scale = np.max(np.abs(arr), axis=axes) + 1e-9
        self._scale = scale
        shape = [1] * arr.ndim
        shape[self._axis] = -1
        return _fake_quant(w, scale.reshape(shape), self._bit_length)

    def scales(self):
        return Tensor(np.asarray(self._scale, np.float32))

    def bit_length(self):
        return self._bit_length

    def quant_axis(self):
        return self._axis


class QuanterFactory:
    """Parity: factory.py — partial-bound quanter constructor."""

    def __init__(self, cls, *args, **kwargs):
        self._cls = cls
        self._args = args
        self._kwargs = kwargs

    def _instance(self):
        return self._cls(*self._args, **self._kwargs)


def quanter(name):
    """Decorator registering a quanter class + factory helper
    (parity: factory.py quanter)."""
    def deco(cls):
        def factory(*a, **k):
            return QuanterFactory(cls, *a, **k)
        globals()[name] = factory
        return cls
    return deco


# ---------------------------------------------------------------------------
# config
# ---------------------------------------------------------------------------
class SingleLayerConfig:
    def __init__(self, activation, weight):
        self._activation = activation
        self._weight = weight

    @property
    def activation(self):
        return self._activation

    @property
    def weight(self):
        return self._weight


class QuantConfig:
    """Parity: config.py:60."""

    def __init__(self, activation=None, weight=None):
        if activation is None and weight is None:
            self._global_config = None
        else:
            self._global_config = SingleLayerConfig(activation, weight)
        self._layer2config: Dict[int, SingleLayerConfig] = {}
        self._prefix2config: Dict[str, SingleLayerConfig] = {}
        self._type2config: Dict[type, SingleLayerConfig] = {}
        self._qat_layer_mapping = {nn.Linear: None, nn.Conv2D: None}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer2config[id(l)] = SingleLayerConfig(activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = (layer_name if isinstance(layer_name, (list, tuple))
                 else [layer_name])
        for n in names:
            self._prefix2config[n] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = (layer_type if isinstance(layer_type, (list, tuple))
                 else [layer_type])
        for t in types:
            self._type2config[t] = SingleLayerConfig(activation, weight)

    def add_qat_layer_mapping(self, source, target):
        self._qat_layer_mapping[source] = target

    def _config_for(self, name: str, layer: Layer):
        """`name` is the FULL dotted path from the model root."""
        if id(layer) in self._layer2config:
            return self._layer2config[id(layer)]
        for prefix, cfg in self._prefix2config.items():
            if name == prefix or name.startswith(prefix + "."):
                return cfg
        for t, cfg in self._type2config.items():
            if isinstance(layer, t):
                return cfg
        return self._global_config

    def _resolve_identities(self, model: Layer):
        """Pin layer-object configs to dotted names BEFORE the model is
        deepcopied (id()s don't survive the copy)."""
        for name, sub in model.named_sublayers(include_self=False):
            if id(sub) in self._layer2config:
                self._prefix2config.setdefault(
                    name, self._layer2config[id(sub)])


# ---------------------------------------------------------------------------
# quantized layer wrappers
# ---------------------------------------------------------------------------
class QuantedLinear(Layer):
    """Linear with fake-quanted activation/weight (parity: nn/quant/qat)."""

    def __init__(self, linear: nn.Linear, cfg: SingleLayerConfig):
        super().__init__()
        self._inner = linear
        self.activation_quanter = (cfg.activation._instance()
                                   if cfg and cfg.activation else None)
        self.weight_quanter = (cfg.weight._instance()
                               if cfg and cfg.weight else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        import paddle_tpu.nn.functional as F
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv: nn.Conv2D, cfg: SingleLayerConfig):
        super().__init__()
        self._inner = conv
        self.activation_quanter = (cfg.activation._instance()
                                   if cfg and cfg.activation else None)
        self.weight_quanter = (cfg.weight._instance()
                               if cfg and cfg.weight else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self._inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        import paddle_tpu.nn.functional as F
        return F.conv2d(x, w, self._inner.bias, self._inner._stride,
                        self._inner._padding, self._inner._dilation,
                        self._inner._groups)


class ObservedLayer(Layer):
    """PTQ wrapper: observer on the input activation."""

    def __init__(self, inner: Layer, cfg: SingleLayerConfig):
        super().__init__()
        self._inner = inner
        self.activation_observer = (cfg.activation._instance()
                                    if cfg and cfg.activation else None)
        self.weight_observer = (cfg.weight._instance()
                                if cfg and cfg.weight else None)

    def forward(self, *args, **kw):
        if self.activation_observer is not None and args:
            self.activation_observer(args[0])
        if self.weight_observer is not None and hasattr(
                self._inner, "weight"):
            self.weight_observer(self._inner.weight)
        return self._inner(*args, **kw)


def _swap_layers(model: Layer, make, prefix=""):
    """Replace eligible sublayers in place (make receives the FULL dotted
    path from the root); returns count."""
    n = 0
    for name, child in list(model.named_children()):
        full = prefix + name if not prefix else f"{prefix}.{name}"
        replacement = make(full, child)
        if replacement is not None:
            setattr(model, name, replacement)
            n += 1
        else:
            n += _swap_layers(child, make, full)
    return n


class QAT:
    """Quantization-aware training (parity: qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False):
        self._config._resolve_identities(model)
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child):
            cfg = self._config._config_for(name, child)
            if cfg is None:
                return None
            if isinstance(child, nn.Linear):
                custom = self._config._qat_layer_mapping.get(nn.Linear)
                return (custom or QuantedLinear)(child, cfg)
            if isinstance(child, nn.Conv2D):
                custom = self._config._qat_layer_mapping.get(nn.Conv2D)
                return (custom or QuantedConv2D)(child, cfg)
            return None

        _swap_layers(model, make)
        return model

    def convert(self, model: Layer, inplace=False):
        """Fold fake-quant into deploy form: weights stored int8 +
        per-layer scale buffers (simulated dequant at run time)."""
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child):
            if isinstance(child, (QuantedLinear, QuantedConv2D)):
                inner = child._inner
                if child.weight_quanter is not None:
                    wq = child.weight_quanter
                    _ = wq(inner.weight)          # ensure scales exist
                    scale = np.asarray(wq.scales()._value)
                    bnt = (1 << (wq.bit_length() - 1)) - 1
                    w = np.asarray(inner.weight._value)
                    axis = wq.quant_axis()
                    shape = [1] * w.ndim
                    if scale.ndim:
                        shape[axis] = -1
                    s = scale.reshape(shape)
                    int_w = np.clip(np.round(w / s * bnt), -bnt, bnt)
                    inner.weight.set_value(
                        (int_w * s / bnt).astype(np.float32))
                    inner.register_buffer(
                        "quant_scale", Tensor(scale.astype(np.float32)))
                return inner
            return None

        _swap_layers(model, make)
        return model


class PTQ:
    """Post-training quantization (parity: ptq.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False):
        self._config._resolve_identities(model)
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child):
            cfg = self._config._config_for(name, child)
            if cfg is None or not isinstance(child, (nn.Linear, nn.Conv2D)):
                return None
            return ObservedLayer(child, cfg)

        _swap_layers(model, make)
        return model

    def convert(self, model: Layer, inplace=False):
        if not inplace:
            model = copy.deepcopy(model)

        def make(name, child):
            if isinstance(child, ObservedLayer):
                inner = child._inner
                if child.weight_observer is not None:
                    scale = np.asarray(child.weight_observer.scales()._value)
                    inner.register_buffer(
                        "quant_scale",
                        Tensor(np.asarray(scale, np.float32)))
                if child.activation_observer is not None:
                    inner.register_buffer(
                        "act_scale",
                        Tensor(np.asarray(
                            child.activation_observer.scales()._value,
                            np.float32)))
                return inner
            return None

        _swap_layers(model, make)
        return model
