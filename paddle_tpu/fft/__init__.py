"""paddle_tpu.fft — discrete Fourier transforms.

Parity: python/paddle/fft.py (reference; kernels
paddle/phi/kernels/cpu/fft_*.cc, fft_c2c/fft_r2c/fft_c2r ops in
paddle/phi/api/yaml/ops.yaml).  TPU-native: every transform is the XLA FFT
HLO via jnp.fft, so forward and VJP both compile; norm conventions follow
numpy exactly like the reference does.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..core.tensor import Tensor
from ..ops._helpers import as_value, wrap

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
           "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _norm(norm):
    return norm if norm in ("backward", "ortho", "forward") else "backward"


def _def_1d(op_name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(
            op_name, lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
            (x,))
    op.__name__ = op_name
    return op


def _def_nd(op_name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(
            op_name, lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
            (x,))
    op.__name__ = op_name
    return op


fft = _def_1d("fft", jnp.fft.fft)
ifft = _def_1d("ifft", jnp.fft.ifft)
rfft = _def_1d("rfft", jnp.fft.rfft)
irfft = _def_1d("irfft", jnp.fft.irfft)
hfft = _def_1d("hfft", jnp.fft.hfft)
ihfft = _def_1d("ihfft", jnp.fft.ihfft)

fftn = _def_nd("fftn", jnp.fft.fftn)
ifftn = _def_nd("ifftn", jnp.fft.ifftn)
rfftn = _def_nd("rfftn", jnp.fft.rfftn)
irfftn = _def_nd("irfftn", jnp.fft.irfftn)


def _def_2d(op_name, ndfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return ndfn(x, s=s, axes=axes, norm=norm)
    op.__name__ = op_name
    return op


fft2 = _def_2d("fft2", fftn)
ifft2 = _def_2d("ifft2", ifftn)
rfft2 = _def_2d("rfft2", rfftn)
irfft2 = _def_2d("irfft2", irfftn)


_SWAP = {"backward": "forward", "forward": "backward", "ortho": "ortho"}


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    # hermitian transform = c2r of the conjugate with fwd/bwd norms
    # swapped (hfft(a,n) == irfft(conj(a),n,norm=swapped)); same rule the
    # reference kernels use for fft_c2r hermitian mode.
    return apply_op("hfftn", lambda v: jnp.fft.irfftn(
        jnp.conj(v), s=s, axes=axes, norm=_SWAP[_norm(norm)]), (x,))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return apply_op("ihfftn", lambda v: jnp.conj(
        jnp.fft.rfftn(v, s=s, axes=axes, norm=_SWAP[_norm(norm)])), (x,))


hfft2 = _def_2d("hfft2", hfftn)
ihfft2 = _def_2d("ihfft2", ihfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return wrap(jnp.fft.fftfreq(n, d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return wrap(jnp.fft.rfftfreq(n, d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda v: jnp.fft.fftshift(v, axes), (x,))


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda v: jnp.fft.ifftshift(v, axes), (x,))
