"""Parity import path: paddle.distribution.transform (the 13 Transform
classes of reference transform.py); implementations in the package
__init__."""
from . import (Transform, AbsTransform, AffineTransform, ChainTransform,
               ExpTransform, IndependentTransform, PowerTransform,
               ReshapeTransform, SigmoidTransform, SoftmaxTransform,
               StackTransform, StickBreakingTransform, TanhTransform)

__all__ = ["Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SigmoidTransform",
           "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
           "TanhTransform"]
