"""paddle.distribution (parity: python/paddle/distribution/ — Distribution
base, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/Gamma/
Exponential/Laplace/LogNormal/Gumbel/Multinomial/Geometric/Poisson,
Transform family + TransformedDistribution, Independent,
kl_divergence/register_kl registry).

TPU-native: every method is a pure jnp function over Tensor values —
sample goes through the framework RNG (traced fold-in keys), log_prob and
friends compile into the surrounding XLA module.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..core.tensor import Tensor
from ..ops.random import next_key

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Beta", "Dirichlet", "Gamma", "Exponential", "Laplace",
           "LogNormal", "Gumbel", "Multinomial", "Geometric", "Poisson",
           "kl_divergence", "register_kl", "Transform", "AffineTransform",
           "ExpTransform", "SigmoidTransform", "TanhTransform",
           "ChainTransform", "AbsTransform", "PowerTransform",
           "SoftmaxTransform", "StickBreakingTransform",
           "TransformedDistribution", "Independent"]


def _v(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(
        x, (jnp.ndarray, jax.Array)) else x


def _t(x):
    return Tensor._from_value(jnp.asarray(x))


def _shape(sample_shape, base_shape):
    return tuple(int(s) for s in sample_shape) + tuple(base_shape)


class Distribution:
    """Parity: paddle.distribution.Distribution."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        if hasattr(self, "rsample"):
            return _t(jax.lax.stop_gradient(self.rsample(shape)._value))
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _t(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """Parity: paddle.distribution.Normal."""

    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _t(jnp.broadcast_to(self.scale, self.batch_shape))

    def rsample(self, shape=()):
        eps = jax.random.normal(next_key(),
                                _shape(shape, self.batch_shape))
        return _t(self.loc + self.scale * eps)

    def sample(self, shape=()):
        return _t(jax.lax.stop_gradient(self.rsample(shape)._value))

    def log_prob(self, value):
        v = _v(value)
        var = self.scale ** 2
        return _t(-((v - self.loc) ** 2) / (2 * var)
                  - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
            self.batch_shape))

    def cdf(self, value):
        return _t(0.5 * (1 + jsp.erf(
            (_v(value) - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        return _t(self.loc + self.scale * math.sqrt(2)
                  * jsp.erfinv(2 * _v(value) - 1))


class LogNormal(Normal):
    def rsample(self, shape=()):
        return _t(jnp.exp(super().rsample(shape)._value))

    sample = rsample

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _t((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def log_prob(self, value):
        v = _v(value)
        logv = jnp.log(v)
        return _t(super().log_prob(_t(logv))._value - logv)

    def entropy(self):
        return _t(super().entropy()._value + self.loc)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.low), jnp.shape(self.high)))

    @property
    def mean(self):
        return _t((self.low + self.high) / 2)

    @property
    def variance(self):
        return _t((self.high - self.low) ** 2 / 12)

    def rsample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape))
        return _t(self.low + (self.high - self.low) * u)

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _t(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low)
                  + jnp.zeros(self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return _t(self.probs)

    @property
    def variance(self):
        return _t(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape))
        return _t((u < self.probs).astype(jnp.float32))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (reference parity)."""
        g = jax.random.logistic(next_key(),
                                _shape(shape, self.batch_shape))
        logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        return _t(jax.nn.sigmoid((logits + g) / temperature))

    def log_prob(self, value):
        v = _v(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return _t(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return _t(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits=None, name=None):
        # reference semantics: `logits` is UNNORMALIZED PROBABILITIES
        # (non-negative, normalized by their sum); under a trace (where
        # sign can't be inspected) fall back to log_softmax
        self.logits = _v(logits)
        try:
            nonneg = bool(np.all(np.asarray(self.logits) >= 0))
        except Exception:          # traced value
            nonneg = False
        if nonneg:
            self._log_p = jnp.log(jnp.clip(
                self.logits / jnp.sum(self.logits, -1, keepdims=True),
                1e-12, 1.0))
        else:
            self._log_p = jax.nn.log_softmax(self.logits)
        super().__init__(jnp.shape(self.logits)[:-1])

    @property
    def probs_normalized(self):
        return jnp.exp(self._log_p)

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        idx = jax.random.categorical(
            next_key(), self._log_p, shape=_shape(
                shape, self.batch_shape))
        return _t(idx.astype(jnp.int64))

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        logp = jnp.broadcast_to(self._log_p,
                                v.shape + self._log_p.shape[-1:])
        return _t(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        return _t(jnp.exp(self.log_prob(value)._value))

    def entropy(self):
        p = jnp.exp(self._log_p)
        return _t(-jnp.sum(p * self._log_p, -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _v(probs)
        super().__init__(jnp.shape(self.probs)[:-1],
                         jnp.shape(self.probs)[-1:])

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1 - self.probs))

    def sample(self, shape=()):
        logp = jnp.log(jnp.clip(self.probs, 1e-12, 1.0))
        idx = jax.random.categorical(
            next_key(), logp,
            shape=_shape(shape, self.batch_shape)
            + (self.total_count,))
        k = self.probs.shape[-1]
        counts = jax.nn.one_hot(idx, k).sum(-2)
        return _t(counts)

    def log_prob(self, value):
        v = _v(value)
        logp = jnp.log(jnp.clip(self.probs, 1e-12, 1.0))
        return _t(jsp.gammaln(self.total_count + 1.0)
                  - jnp.sum(jsp.gammaln(v + 1.0), -1)
                  + jnp.sum(v * logp, -1))

    def entropy(self):
        # no closed form; Monte-Carlo like the reference's approximation
        s = self.sample((128,))
        return _t(-jnp.mean(self.log_prob(s)._value, 0))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.alpha), jnp.shape(self.beta)))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _t(self.alpha * self.beta / (s ** 2 * (s + 1)))

    def rsample(self, shape=()):
        return _t(jax.random.beta(next_key(), self.alpha, self.beta,
                                  _shape(shape, self.batch_shape)))

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        return _t((self.alpha - 1) * jnp.log(v)
                  + (self.beta - 1) * jnp.log1p(-v)
                  - _betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return _t(_betaln(a, b) - (a - 1) * jsp.digamma(a)
                  - (b - 1) * jsp.digamma(b)
                  + (a + b - 2) * jsp.digamma(a + b))


def _betaln(a, b):
    return jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(jnp.shape(self.concentration)[:-1],
                         jnp.shape(self.concentration)[-1:])

    @property
    def mean(self):
        return _t(self.concentration
                  / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        a = self.concentration
        return _t(a * (a0 - a) / (a0 ** 2 * (a0 + 1)))

    def rsample(self, shape=()):
        return _t(jax.random.dirichlet(
            next_key(), self.concentration,
            _shape(shape, self.batch_shape)))

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        a = self.concentration
        return _t(jnp.sum((a - 1) * jnp.log(v), -1)
                  + jsp.gammaln(jnp.sum(a, -1))
                  - jnp.sum(jsp.gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        return _t(jnp.sum(jsp.gammaln(a), -1) - jsp.gammaln(a0)
                  + (a0 - k) * jsp.digamma(a0)
                  - jnp.sum((a - 1) * jsp.digamma(a), -1))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.concentration), jnp.shape(self.rate)))

    @property
    def mean(self):
        return _t(self.concentration / self.rate)

    @property
    def variance(self):
        return _t(self.concentration / self.rate ** 2)

    def rsample(self, shape=()):
        g = jax.random.gamma(next_key(), self.concentration,
                             _shape(shape, self.batch_shape))
        return _t(g / self.rate)

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        a, b = self.concentration, self.rate
        return _t(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                  - jsp.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return _t(a - jnp.log(b) + jsp.gammaln(a)
                  + (1 - a) * jsp.digamma(a))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return _t(1.0 / self.rate)

    @property
    def variance(self):
        return _t(1.0 / self.rate ** 2)

    def rsample(self, shape=()):
        e = jax.random.exponential(next_key(),
                                   _shape(shape, self.batch_shape))
        return _t(e / self.rate)

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        return _t(jnp.where(v >= 0, jnp.log(self.rate) - self.rate * v,
                            -jnp.inf))

    def entropy(self):
        return _t(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _t(2 * self.scale ** 2)

    @property
    def stddev(self):
        return _t(math.sqrt(2) * self.scale)

    def rsample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape),
                               minval=-0.5 + 1e-7, maxval=0.5)
        return _t(self.loc - self.scale * jnp.sign(u)
                  * jnp.log1p(-2 * jnp.abs(u)))

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        return _t(-jnp.abs(v - self.loc) / self.scale
                  - jnp.log(2 * self.scale))

    def entropy(self):
        return _t(1 + jnp.log(2 * self.scale)
                  + jnp.zeros(self.batch_shape))

    def cdf(self, value):
        v = _v(value)
        z = (v - self.loc) / self.scale
        return _t(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        p = _v(value)
        term = p - 0.5
        return _t(self.loc - self.scale * jnp.sign(term)
                  * jnp.log1p(-2 * jnp.abs(term)))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.loc), jnp.shape(self.scale)))

    @property
    def mean(self):
        return _t(self.loc + self.scale * np.euler_gamma)

    @property
    def variance(self):
        return _t(math.pi ** 2 / 6 * self.scale ** 2)

    def rsample(self, shape=()):
        g = jax.random.gumbel(next_key(),
                              _shape(shape, self.batch_shape))
        return _t(self.loc + self.scale * g)

    sample = rsample

    def log_prob(self, value):
        z = (_v(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return _t(jnp.log(self.scale) + 1 + np.euler_gamma
                  + jnp.zeros(self.batch_shape))


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _v(probs)
        super().__init__(jnp.shape(self.probs))

    @property
    def mean(self):
        return _t(1.0 / self.probs)

    @property
    def variance(self):
        return _t((1 - self.probs) / self.probs ** 2)

    def sample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape),
                               minval=1e-7, maxval=1.0)
        return _t(jnp.ceil(jnp.log(u) / jnp.log1p(-self.probs)))

    def log_prob(self, value):
        k = _v(value)
        return _t((k - 1) * jnp.log1p(-self.probs)
                  + jnp.log(self.probs))

    def entropy(self):
        p = self.probs
        return _t(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(jnp.shape(self.rate))

    @property
    def mean(self):
        return _t(self.rate)

    @property
    def variance(self):
        return _t(self.rate)

    def sample(self, shape=()):
        return _t(jax.random.poisson(
            next_key(), self.rate,
            _shape(shape, self.batch_shape)).astype(jnp.float32))

    def log_prob(self, value):
        k = _v(value)
        return _t(k * jnp.log(self.rate) - self.rate
                  - jsp.gammaln(k + 1.0))

    def entropy(self):
        s = self.sample((128,))
        return _t(-jnp.mean(self.log_prob(s)._value, 0))


# ---------------------------------------------------------------------------
# transforms
# ---------------------------------------------------------------------------
class Transform:
    """Parity: paddle.distribution.Transform."""

    def forward(self, x):
        return _t(self._forward(_v(x)))

    def inverse(self, y):
        return _t(self._inverse(_v(y)))

    def forward_log_det_jacobian(self, x):
        return _t(self._fldj(_v(x)))

    def inverse_log_det_jacobian(self, y):
        return _t(-self._fldj(self._inverse(_v(y))))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _v(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def _fldj(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _fldj(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("softmax is not bijective")


class StickBreakingTransform(Transform):
    def _forward(self, x):
        # R^k -> k+1 simplex
        z = jax.nn.sigmoid(x - jnp.log(
            x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)))
        zp = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,), z.dtype)],
                             -1)
        rest = jnp.concatenate(
            [jnp.ones(z.shape[:-1] + (1,), z.dtype),
             jnp.cumprod(1 - z, -1)], -1)
        return zp * rest

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype),
             jnp.cumsum(y[..., :-1], -1)], -1)[..., :k]
        z = y[..., :k] / jnp.clip(1 - cum, 1e-12)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(
            k - jnp.arange(k, dtype=y.dtype))

    def _fldj(self, x):
        k = x.shape[-1]
        offset = jnp.log(k - jnp.arange(k, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        # sum of log sigma(xo) + log(1-sigma(xo)) + cumulative stick mass
        return jnp.sum(
            -jax.nn.softplus(-xo) - jax.nn.softplus(xo)
            + jnp.concatenate(
                [jnp.zeros(x.shape[:-1] + (1,), x.dtype),
                 jnp.cumsum(jnp.log1p(-z[..., :-1]), -1)], -1), -1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    """Parity: paddle.distribution.TransformedDistribution."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = (transforms if isinstance(transforms, (list,
                                                                 tuple))
                           else [transforms])
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)._value
        for t in self.transforms:
            x = t._forward(x)
        return _t(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)._value
        for t in self.transforms:
            x = t._forward(x)
        return _t(x)

    def log_prob(self, value):
        y = _v(value)
        ldj = 0.0
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ldj = ldj + t._fldj(x)
            y = x
        return _t(self.base.log_prob(_t(y))._value - ldj)


class Independent(Distribution):
    """Parity: paddle.distribution.Independent — reinterprets batch dims
    as event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bs = base.batch_shape
        super().__init__(bs[:len(bs) - self.rank],
                         bs[len(bs) - self.rank:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._value
        return _t(jnp.sum(lp, axis=tuple(range(-self.rank, 0))))

    def entropy(self):
        e = self.base.entropy()._value
        return _t(jnp.sum(e, axis=tuple(range(-self.rank, 0))))


# ---------------------------------------------------------------------------
# KL registry
# ---------------------------------------------------------------------------
_KL_REGISTRY: Dict[Tuple[type, type], callable] = {}


def register_kl(p_cls, q_cls):
    """Parity: paddle.distribution.register_kl."""
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    matches = [((pc, qc), fn) for (pc, qc), fn in _KL_REGISTRY.items()
               if isinstance(p, pc) and isinstance(q, qc)]
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    # most-derived registration wins (subclass KLs shadow base ones)
    (pc, qc), fn = min(
        matches, key=lambda m: (type(p).__mro__.index(m[0][0])
                                + type(q).__mro__.index(m[0][1])))
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return _t(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _t(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    pp = jnp.exp(p._log_p)
    return _t(jnp.sum(pp * (p._log_p - q._log_p), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return _t(pp * (jnp.log(pp) - jnp.log(qq))
              + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq)))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    return _t(_betaln(q.alpha, q.beta) - _betaln(p.alpha, p.beta)
              + (p.alpha - q.alpha) * jsp.digamma(p.alpha)
              + (p.beta - q.beta) * jsp.digamma(p.beta)
              + (q.alpha - p.alpha + q.beta - p.beta)
              * jsp.digamma(p.alpha + p.beta))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1)
    return _t(jsp.gammaln(a0) - jnp.sum(jsp.gammaln(a), -1)
              - jsp.gammaln(jnp.sum(b, -1)) + jnp.sum(jsp.gammaln(b), -1)
              + jnp.sum((a - b) * (jsp.digamma(a)
                                   - jsp.digamma(a0)[..., None]), -1))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    a, b = p.concentration, p.rate
    c, d = q.concentration, q.rate
    return _t((a - c) * jsp.digamma(a) - jsp.gammaln(a) + jsp.gammaln(c)
              + c * (jnp.log(b) - jnp.log(d)) + a * (d - b) / b)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return _t(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    scale_ratio = p.scale / q.scale
    delta = jnp.abs(p.loc - q.loc) / q.scale
    return _t(-jnp.log(scale_ratio) + scale_ratio
              * jnp.exp(-jnp.abs(p.loc - q.loc) / p.scale)
              + delta - 1)


# ---------------------------------------------------------------------------
# round-5 tail: ExponentialFamily, Cauchy, ContinuousBernoulli, Binomial,
# MultivariateNormal (parity: python/paddle/distribution/
# exponential_family.py, cauchy.py, continuous_bernoulli.py, binomial.py,
# multivariate_normal.py)
# ---------------------------------------------------------------------------
class ExponentialFamily(Distribution):
    """Parity: distribution/exponential_family.py — base class whose
    generic ``entropy`` is derived from the log normalizer via autodiff
    (Bregman form: H = A(eta) - sum eta_i * dA/deta_i + E[-h(x)]),
    exactly the reference's _entropy built on paddle.grad."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nparams = [jnp.asarray(p, jnp.float32)
                   for p in self._natural_parameters]
        lg, grads = jax.value_and_grad(
            lambda ps: jnp.sum(self._log_normalizer(*ps)),
            argnums=0)(tuple(nparams))
        ent = -self._mean_carrier_measure + lg
        for np_, g in zip(nparams, grads):
            ent = ent - np_ * g
        return _t(ent)


class Cauchy(Distribution):
    """Parity: distribution/cauchy.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.loc),
                                              jnp.shape(self.scale)))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def rsample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape),
                               minval=1e-7, maxval=1.0 - 1e-7)
        return _t(self.loc + self.scale * jnp.tan(jnp.pi * (u - 0.5)))

    def sample(self, shape=()):
        return _t(jax.lax.stop_gradient(self.rsample(shape)._value))

    def log_prob(self, value):
        v = _v(value)
        z = (v - self.loc) / self.scale
        return _t(-jnp.log(jnp.pi) - jnp.log(self.scale)
                  - jnp.log1p(z * z))

    def cdf(self, value):
        v = _v(value)
        return _t(jnp.arctan((v - self.loc) / self.scale) / jnp.pi + 0.5)

    def entropy(self):
        return _t(jnp.broadcast_to(
            jnp.log(4 * jnp.pi * self.scale), self.batch_shape))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class ContinuousBernoulli(Distribution):
    """Parity: distribution/continuous_bernoulli.py (probs param;
    lims window around 0.5 uses the Taylor form like the reference)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _v(probs)
        self._lims = lims
        super().__init__(jnp.shape(self.probs))

    def _cut(self):
        lo, hi = self._lims
        return (self.probs < lo) | (self.probs > hi)

    def _log_norm(self):
        # C(p) = 2 atanh(1-2p) / (1-2p) for p != 0.5 ; 2 at p = 0.5
        p = jnp.where(self._cut(), self.probs, 0.45)   # safe operand
        val = jnp.log(2.0 * jnp.arctanh(1.0 - 2.0 * p)
                      / (1.0 - 2.0 * p))
        # 2nd-order Taylor around 0.5: log(2 + 8/3 e^2), e = p - 0.5
        e = self.probs - 0.5
        taylor = jnp.log(2.0) + 4.0 / 3.0 * e * e
        return jnp.where(self._cut(), val, taylor)

    @property
    def mean(self):
        p = jnp.where(self._cut(), self.probs, 0.45)
        m = p / (2.0 * p - 1.0) \
            + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * p))
        e = self.probs - 0.5
        taylor = 0.5 + e / 3.0
        return _t(jnp.where(self._cut(), m, taylor))

    @property
    def variance(self):
        p = jnp.where(self._cut(), self.probs, 0.45)
        v = p * (p - 1.0) / jnp.square(1.0 - 2.0 * p) \
            + 1.0 / jnp.square(2.0 * jnp.arctanh(1.0 - 2.0 * p))
        e = self.probs - 0.5
        taylor = 1.0 / 12.0 - 2.0 / 15.0 * e * e
        return _t(jnp.where(self._cut(), v, taylor))

    def rsample(self, shape=()):
        u = jax.random.uniform(next_key(),
                               _shape(shape, self.batch_shape),
                               minval=1e-6, maxval=1.0 - 1e-6)
        return self.icdf(_t(u))

    sample = rsample

    def log_prob(self, value):
        v = _v(value)
        return _t(v * jnp.log(self.probs)
                  + (1.0 - v) * jnp.log1p(-self.probs)
                  + self._log_norm())

    def cdf(self, value):
        v = _v(value)
        p = jnp.where(self._cut(), self.probs, 0.45)
        num = (jnp.power(p, v) * jnp.power(1.0 - p, 1.0 - v)
               + p - 1.0)
        c = num / (2.0 * p - 1.0)
        return _t(jnp.clip(jnp.where(self._cut(), c, v), 0.0, 1.0))

    def icdf(self, value):
        u = _v(value)
        p = jnp.where(self._cut(), self.probs, 0.45)
        ratio = jnp.log1p(-p) - jnp.log(p)
        x = (jnp.log1p(u * jnp.expm1(ratio))) / ratio
        return _t(jnp.where(self._cut(), x, u))

    def entropy(self):
        m = self.mean._value
        return _t(-(m * jnp.log(self.probs)
                    + (1.0 - m) * jnp.log1p(-self.probs)
                    + self._log_norm()))


class Binomial(Distribution):
    """Parity: distribution/binomial.py (total_count, probs)."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _v(total_count)
        self.probs = _v(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), jnp.shape(self.probs)))

    @property
    def mean(self):
        return _t(self.total_count * self.probs)

    @property
    def variance(self):
        return _t(self.total_count * self.probs * (1.0 - self.probs))

    def sample(self, shape=()):
        n = jnp.broadcast_to(self.total_count, self.batch_shape)
        p = jnp.broadcast_to(self.probs, self.batch_shape)
        out = jax.random.binomial(next_key(), n.astype(jnp.float32), p,
                                  shape=_shape(shape, self.batch_shape))
        return _t(out.astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _v(value)
        n = self.total_count
        logp = jnp.log(self.probs)
        log1mp = jnp.log1p(-self.probs)
        return _t(gammaln(n + 1.0) - gammaln(v + 1.0)
                  - gammaln(n - v + 1.0) + v * logp + (n - v) * log1mp)

    def entropy(self):
        # exact finite sum over the support (reference computes the
        # same sum); vectorized over [0, max_n]
        n_max = int(np.max(np.asarray(self.total_count)))
        ks = jnp.arange(n_max + 1, dtype=jnp.float32)
        grid = ks.reshape((-1,) + (1,) * len(self.batch_shape))
        lp = self.log_prob(_t(jnp.broadcast_to(
            grid, (n_max + 1,) + tuple(self.batch_shape))))._value
        valid = grid <= self.total_count
        return _t(-jnp.sum(jnp.where(valid, jnp.exp(lp) * lp, 0.0),
                           axis=0))


class MultivariateNormal(Distribution):
    """Parity: distribution/multivariate_normal.py (loc +
    covariance_matrix / precision_matrix / scale_tril)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _v(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError(
                "exactly one of covariance_matrix, precision_matrix, "
                "scale_tril must be given")
        if scale_tril is not None:
            self._scale_tril = _v(scale_tril)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(_v(covariance_matrix))
        else:
            prec_chol = jnp.linalg.cholesky(_v(precision_matrix))
            eye = jnp.eye(prec_chol.shape[-1], dtype=prec_chol.dtype)
            self._scale_tril = jax.scipy.linalg.solve_triangular(
                prec_chol, eye, lower=True, trans=1)
        d = self._scale_tril.shape[-1]
        batch = jnp.broadcast_shapes(jnp.shape(self.loc)[:-1],
                                     jnp.shape(self._scale_tril)[:-2])
        super().__init__(batch, (d,))

    @property
    def scale_tril(self):
        return _t(self._scale_tril)

    @property
    def covariance_matrix(self):
        L = self._scale_tril
        return _t(L @ jnp.swapaxes(L, -1, -2))

    @property
    def precision_matrix(self):
        cov = self.covariance_matrix._value
        return _t(jnp.linalg.inv(cov))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(
            self.loc, self.batch_shape + self.event_shape))

    @property
    def variance(self):
        L = self._scale_tril
        var = jnp.sum(jnp.square(L), axis=-1)
        return _t(jnp.broadcast_to(
            var, self.batch_shape + self.event_shape))

    def rsample(self, shape=()):
        shp = _shape(shape, self.batch_shape + self.event_shape)
        eps = jax.random.normal(next_key(), shp)
        return _t(self.loc + jnp.einsum("...ij,...j->...i",
                                        self._scale_tril, eps))

    sample = Distribution.sample

    def log_prob(self, value):
        v = _v(value)
        diff = v - self.loc
        y = jax.scipy.linalg.solve_triangular(
            self._scale_tril, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(jnp.square(y), axis=-1)
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1)
        d = self.event_shape[0]
        return _t(-0.5 * (d * jnp.log(2 * jnp.pi) + maha) - half_logdet)

    def entropy(self):
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), axis=-1)
        d = self.event_shape[0]
        ent = 0.5 * d * (1.0 + jnp.log(2 * jnp.pi)) + half_logdet
        return _t(jnp.broadcast_to(ent, self.batch_shape))

    def kl_divergence(self, other):
        return kl_divergence(self, other)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    Lp, Lq = p._scale_tril, q._scale_tril
    d = p.event_shape[0]
    half_logdet_p = jnp.sum(jnp.log(
        jnp.diagonal(Lp, axis1=-2, axis2=-1)), axis=-1)
    half_logdet_q = jnp.sum(jnp.log(
        jnp.diagonal(Lq, axis1=-2, axis2=-1)), axis=-1)
    M = jax.scipy.linalg.solve_triangular(Lq, Lp, lower=True)
    tr = jnp.sum(jnp.square(M), axis=(-2, -1))
    diff = q.loc - p.loc
    y = jax.scipy.linalg.solve_triangular(
        Lq, diff[..., None], lower=True)[..., 0]
    maha = jnp.sum(jnp.square(y), axis=-1)
    return _t(half_logdet_q - half_logdet_p + 0.5 * (tr + maha - d))


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    """Closed form (reference cauchy.py kl_divergence):
    log(((s_p + s_q)^2 + (l_p - l_q)^2) / (4 s_p s_q))."""
    return _t(jnp.log(
        (jnp.square(p.scale + q.scale) + jnp.square(p.loc - q.loc))
        / (4.0 * p.scale * q.scale)))


__all__ += ["ExponentialFamily", "Cauchy", "ContinuousBernoulli",
            "Binomial", "MultivariateNormal"]


class IndependentTransform(Transform):
    """Parity: transform.py IndependentTransform — reinterpret the
    rightmost ``reinterpreted_batch_rank`` dims as event dims (sums the
    log-det over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._rank = int(reinterpreted_batch_rank)

    def _forward(self, x):
        return self._base._forward(x)

    def _inverse(self, y):
        return self._base._inverse(y)

    def _fldj(self, x):
        ld = self._base._fldj(x)
        axes = tuple(range(-self._rank, 0))
        return jnp.sum(ld, axis=axes)


class ReshapeTransform(Transform):
    """Parity: transform.py ReshapeTransform (in_event_shape ->
    out_event_shape; volume-preserving, log-det 0)."""

    def __init__(self, in_event_shape, out_event_shape):
        self._in = tuple(int(s) for s in in_event_shape)
        self._out = tuple(int(s) for s in out_event_shape)
        if int(np.prod(self._in)) != int(np.prod(self._out)):
            raise ValueError(
                f"in_event_shape {self._in} and out_event_shape "
                f"{self._out} have different sizes")

    @property
    def in_event_shape(self):
        return self._in

    @property
    def out_event_shape(self):
        return self._out

    def _forward(self, x):
        batch = x.shape[: x.ndim - len(self._in)]
        return x.reshape(batch + self._out)

    def _inverse(self, y):
        batch = y.shape[: y.ndim - len(self._out)]
        return y.reshape(batch + self._in)

    def _fldj(self, x):
        batch = x.shape[: x.ndim - len(self._in)]
        return jnp.zeros(batch, x.dtype)


class StackTransform(Transform):
    """Parity: transform.py StackTransform — apply a list of transforms
    to slices of ``x`` along ``axis``."""

    def __init__(self, transforms, axis=0):
        self._transforms = list(transforms)
        self._axis = int(axis)

    def _map(self, method, x):
        slices = jnp.moveaxis(x, self._axis, 0)
        outs = [getattr(t, method)(slices[i])
                for i, t in enumerate(self._transforms)]
        return jnp.moveaxis(jnp.stack(outs), 0, self._axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)


__all__ += ["IndependentTransform", "ReshapeTransform", "StackTransform"]
