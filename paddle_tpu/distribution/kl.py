"""Parity import path: paddle.distribution.kl (__all__ = [kl_divergence,
register_kl]); implementations in the package __init__."""
from . import kl_divergence, register_kl

__all__ = ["kl_divergence", "register_kl"]
