"""Custom C++ op extension.

Parity: the reference's custom-operator seam (paddle/extension.h
PD_BUILD_OP + python/paddle/utils/cpp_extension/ — user-compiled ops
loaded and registered at import).

TPU-native contract: XLA owns device codegen, so a custom C++ op runs as
a HOST kernel bridged into traced programs via ``jax.pure_callback`` (the
io_callback seam — XLA calls back into the host while the surrounding
program stays compiled).  That is the honest TPU analog of the
reference's CPU custom kernels; custom *device* kernels are written in
Pallas instead (see ops/pallas_kernels.py).

C ABI (v1, elementwise/same-shape family):

    extern "C" void <op>(const float** inputs, int32_t n_inputs,
                         float* out, int64_t numel);

Each op compiled from `sources` is bound as a framework op: Tensor in/out,
AMP/tape/jit aware through the normal dispatch choke point; gradients are
attached with ``.def_vjp`` (a Python/paddle function, or another C op).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["load", "get_build_directory", "CppExtension", "CustomOp"]


from .._native_build import build_shared_lib


def get_build_directory() -> str:
    d = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "extensions")
    os.makedirs(d, exist_ok=True)
    return d


def _compile(name: str, sources: Sequence[str], extra_cflags, verbose):
    return build_shared_lib(name, sources, extra_cflags,
                            cache_subdir="extensions", verbose=verbose)


class CustomOp:
    """One bound C op, callable on Tensors, traceable, vjp-extensible."""

    def __init__(self, name: str, cfunc):
        self.name = name
        self._c = cfunc
        self._c.restype = None
        self._c.argtypes = [ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                            ctypes.c_int32, ctypes.POINTER(ctypes.c_float),
                            ctypes.c_int64]
        self._vjp: Optional[Callable] = None
        self._build_traceable()

    def _host_call(self, *arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        out = np.empty_like(arrays[0])
        ptrs = (ctypes.POINTER(ctypes.c_float) * len(arrays))(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for a in arrays])
        self._c(ptrs, len(arrays), out.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)), out.size)
        return out

    def _build_traceable(self):
        host = self._host_call
        name = self.name

        def callback_fn(*vals):
            shape_dtype = jax.ShapeDtypeStruct(vals[0].shape, jnp.float32)
            return jax.pure_callback(host, shape_dtype, *vals,
                                     vmap_method="sequential")

        op = jax.custom_vjp(callback_fn)

        def fwd(*vals):
            return callback_fn(*vals), vals

        def bwd(res, g):
            if self._vjp is None:
                raise RuntimeError(
                    f"custom op '{name}' has no gradient: attach one with "
                    f".def_vjp(fn) before differentiating through it")
            from ..core.tensor import Tensor
            outs = self._vjp(*[Tensor._from_value(v) for v in res],
                             Tensor._from_value(g))
            outs = outs if isinstance(outs, (list, tuple)) else [outs]
            flat = []
            for v, o in zip(res, list(outs) + [None] * len(res)):
                if o is None:
                    flat.append(jnp.zeros_like(v))
                else:
                    flat.append(o._value if isinstance(o, Tensor) else o)
            return tuple(flat)

        op.defvjp(fwd, bwd)
        self._traceable = op

    def def_vjp(self, fn: Callable):
        """fn(*inputs, grad_out) -> grad(s) w.r.t. inputs (Tensor math)."""
        self._vjp = fn
        return self

    def __call__(self, *tensors):
        from ..core.dispatch import apply_op
        return apply_op(f"custom.{self.name}", self._traceable, tensors)


class _OpModule:
    def __init__(self, ops: Dict[str, CustomOp]):
        self._ops = ops
        for k, v in ops.items():
            setattr(self, k, v)

    def __iter__(self):
        return iter(self._ops.values())


def load(name: str, sources: Sequence[str], functions: Sequence[str],
         extra_cflags: Optional[List[str]] = None, verbose: bool = False,
         **kw) -> _OpModule:
    """Compile `sources` and bind each exported op in `functions`.

    Parity: paddle.utils.cpp_extension.load (JIT build + import); the op
    list replaces PD_BUILD_OP discovery (no C++ static registrars in a
    plain dlopen'd lib)."""
    so_path = _compile(name, sources, extra_cflags, verbose)
    lib = ctypes.CDLL(so_path)
    ops = {}
    for fname in functions:
        try:
            cfunc = getattr(lib, fname)
        except AttributeError:
            raise RuntimeError(
                f"{so_path} does not export '{fname}' — declare it "
                f"extern \"C\"") from None
        ops[fname] = CustomOp(fname, cfunc)
    return _OpModule(ops)


class CppExtension:
    """setuptools-style descriptor (parity:
    paddle.utils.cpp_extension.CppExtension); use with load() here."""

    def __init__(self, sources, *a, **kw):
        self.sources = sources


class BuildExtension:
    """setuptools command shim (parity: cpp_extension.BuildExtension);
    ``setup`` drives the in-tree compiler directly, so this carries only
    the options the reference command accepts."""

    @classmethod
    def with_options(cls, **options):
        return cls

    def __init__(self, *a, **kw):
        pass


def setup(name=None, ext_modules=None, **kwargs):
    """Parity: paddle.utils.cpp_extension.setup — build the extension's
    sources into a shared library under the build directory (the
    ``python setup.py install`` flow of the reference collapses to the
    same in-tree g++ compile that ``load`` uses; import the ops with
    ``load(name, sources, functions)``)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules]
    built = []
    for ext in exts:
        if ext is None:
            continue
        sources = getattr(ext, "sources", ext)
        ext_name = getattr(ext, "name", None) or name or "custom_ops"
        so_path = _compile(ext_name, sources,
                           kwargs.get("extra_cflags"),
                           kwargs.get("verbose", False))
        built.append(so_path)
    return built


__all__ += ["BuildExtension", "setup"]
