"""Parity: python/paddle/utils/download.py (get_weights_path_from_url).
This environment has no egress; cache hits (and file:// URLs) work, a
genuine network fetch raises with a clear message."""
from __future__ import annotations

import os
import shutil

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hapi/weights")


def get_weights_path_from_url(url: str, md5sum=None) -> str:
    """Resolve ``url`` to a local weights path via the cache directory
    (reference keeps the same layout under ~/.cache/paddle/hapi)."""
    os.makedirs(WEIGHTS_HOME, exist_ok=True)
    fname = os.path.basename(url.split("?")[0])
    target = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(target):
        return target
    if url.startswith("file://"):
        shutil.copy(url[len("file://"):], target)
        return target
    raise RuntimeError(
        f"weights {fname!r} not in cache ({WEIGHTS_HOME}) and this "
        "environment has no network egress; place the file there "
        "manually or pass a file:// URL")
