"""DLPack interop (parity: python/paddle/utils/dlpack.py — to_dlpack /
from_dlpack).  TPU-native: jax arrays speak dlpack directly; CPU-backed
arrays exchange zero-copy with torch/numpy."""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack capsule."""
    v = x._value if isinstance(x, Tensor) else x
    return v.__dlpack__()


class _CapsuleHolder:
    """Adapter giving a raw PyCapsule the array-API dlpack protocol
    (modern jax/numpy consume only objects, not bare capsules)."""

    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, **kwargs):
        return self._capsule

    def __dlpack_device__(self):
        return (1, 0)          # kDLCPU; host staging is the exchange path


def from_dlpack(dlpack):
    """DLPack capsule (or any object with __dlpack__) -> Tensor."""
    import numpy as np
    import jax.numpy as jnp
    if not hasattr(dlpack, "__dlpack__"):
        dlpack = _CapsuleHolder(dlpack)   # reference API passes capsules
    try:
        return Tensor._from_value(jnp.from_dlpack(dlpack))
    except (TypeError, RuntimeError):
        # jax rejects some producers (e.g. unaligned/readonly): stage
        # through numpy's dlpack import instead
        return Tensor._from_value(jnp.asarray(np.from_dlpack(dlpack)))
