"""paddle.utils (parity: python/paddle/utils/ — cpp_extension, unique_name,
deprecated/try_import helpers)."""
from __future__ import annotations

import importlib
import threading
import warnings

from . import cpp_extension

__all__ = ["cpp_extension", "unique_name", "deprecated", "try_import",
           "run_check"]


class _UniqueName:
    """Parity: paddle.utils.unique_name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._prefix = []

    def generate(self, key: str = "") -> str:
        with self._lock:
            c = self._counters.get(key, 0)
            self._counters[key] = c + 1
        prefix = "".join(self._prefix)
        return f"{prefix}{key}_{c}"

    def guard(self, new_generator=None):
        gen = self
        prefix = new_generator or ""

        class _G:
            def __enter__(self):
                gen._prefix.append(prefix)

            def __exit__(self, *exc):
                gen._prefix.pop()
                return False

        return _G()

    def switch(self, new_generator=None):
        self._counters = {}


unique_name = _UniqueName()


def deprecated(update_to="", since="", reason="", level=0):
    def wrap(fn):
        def inner(*a, **k):
            warnings.warn(
                f"API {fn.__name__} is deprecated since {since}: {reason}. "
                f"Use {update_to} instead.", DeprecationWarning)
            return fn(*a, **k)
        inner.__name__ = fn.__name__
        inner.__doc__ = fn.__doc__
        return inner
    return wrap


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        msg = err_msg or (
            f"'{module_name}' is required but not installed; this "
            f"environment has no network egress, so vendor it or gate "
            f"the feature.")
        raise ImportError(msg) from None


def run_check():
    """Parity: paddle.utils.run_check — is the framework usable?"""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).sum()
    assert float(np.asarray(y._value)) == 8.0
    n = paddle.device.device_count() if paddle.device else 1
    print(f"PaddleTPU works! devices: {n}")


def require_version(min_version: str, max_version=None):
    """Parity: paddle.utils.require_version — check the installed
    framework version against [min_version, max_version].  Raises
    ValueError/TypeError exactly like the reference on malformed input
    or unsatisfied bounds."""
    if not isinstance(min_version, str):
        raise TypeError(f"min_version must be str, got {type(min_version)}")
    if max_version is not None and not isinstance(max_version, str):
        raise TypeError(f"max_version must be str or None, "
                        f"got {type(max_version)}")
    import re as _re
    ver_pat = _re.compile(r"^\d+(\.\d+){0,3}$")
    if not ver_pat.match(min_version):
        raise ValueError(f"invalid min_version {min_version!r}")
    if max_version is not None and not ver_pat.match(max_version):
        raise ValueError(f"invalid max_version {max_version!r}")
    from .. import __version__

    def parts(v):
        return [int(x) for x in v.split(".")] + [0] * (4 - len(v.split(".")))

    cur = parts(__version__.split("+")[0].split("rc")[0])
    if parts(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parts(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True


from . import dlpack          # noqa: E402,F401
from . import download        # noqa: E402,F401
__all__ += ["require_version", "dlpack", "download"]
