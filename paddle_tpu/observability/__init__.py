"""paddle_tpu.observability — unified runtime metrics + telemetry.

The production-observability layer SURVEY §5.5 notes the reference
lacks in-repo: a process-wide :class:`MetricsRegistry` of labeled
Counter/Gauge/Histogram instruments, per-step :class:`StepTelemetry`
(wall time, tokens/s, MFU from the compiled step's ``cost_analysis()``,
live/peak HBM, NaN/Inf loss sentinel), Prometheus text / HTTP
(`/metrics`, `/healthz`) / JSON exporters, and a merger folding host
``RecordEvent`` spans, runtime step/checkpoint/comm markers and the
``jax.profiler`` device trace into one chrome://tracing JSON.

Every built-in subsystem records into :func:`default_registry`:

========================  =================================================
subsystem                 metric families
========================  =================================================
Engine.fit                train_steps_total, train_step_duration_seconds,
                          train_tokens_per_second, train_mfu_ratio,
                          train_checkpoint_stall_seconds,
                          train_resume_total, hbm_in_use_bytes
ContinuousBatchingEngine  serving_queue_depth, serving_slot_occupancy_ratio,
                          serving_kv_page_utilization_ratio,
                          serving_prefill_duration_seconds,
                          serving_decode_step_duration_seconds,
                          serving_ttft_seconds, serving_tpot_seconds,
                          serving_requests_total, serving_tokens_total,
                          serving_truncated_victims_total
ServingRouter             router_requests_total, router_pending_depth,
                          router_prefix_route_hits_total,
                          router_requeues_total, router_engine_healthy,
                          router_slo_attained_total,
                          router_latency_quantile_seconds
RequestTracer             request_trace_spans_total,
                          request_trace_dropped_spans_total
CheckpointManager         checkpoint_save_duration_seconds,
                          checkpoint_written_bytes_total,
                          checkpoint_commits_total,
                          checkpoint_gc_removed_total,
                          checkpoint_failures_total
DataLoader                dataloader_queue_wait_seconds
comm_watchdog             comm_timeouts_total, comm_aborts_total
========================  =================================================
"""
from .metrics import (MetricsRegistry, Counter, Gauge, Histogram,
                      MetricError, DEFAULT_BUCKETS, default_registry,
                      counter, gauge, histogram)
from .exporters import (generate_latest, json_snapshot, dump_json,
                        MetricsServer, start_metrics_server,
                        METRICS_PORT_ENV, set_health_provider,
                        healthz_payload)
from .telemetry import (StepTelemetry, device_peak_flops,
                        PEAK_FLOPS_BY_KIND, CHECK_NAN_ENV,
                        PEAK_FLOPS_ENV)
from .trace_merge import (SpanLog, span_log, record_span, record_instant,
                          merge_chrome_trace, load_device_trace_events)
from .request_trace import (RequestTracer, NullRequestTracer,
                            NULL_TRACER, resolve_tracer,
                            LatencyReservoir, validate_span_chain,
                            fleet_trace)
from .capacity import (SignalWindow, EngineCapacityMonitor,
                       CapacityConfig, CapacityPlanner,
                       FleetCapacityMonitor, resolve_capacity_monitor,
                       CAPACITY_ACTIONS)

__all__ = [
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "MetricError",
    "DEFAULT_BUCKETS", "default_registry", "counter", "gauge",
    "histogram",
    "generate_latest", "json_snapshot", "dump_json", "MetricsServer",
    "start_metrics_server", "METRICS_PORT_ENV",
    "set_health_provider", "healthz_payload",
    "StepTelemetry", "device_peak_flops", "PEAK_FLOPS_BY_KIND",
    "CHECK_NAN_ENV", "PEAK_FLOPS_ENV",
    "SpanLog", "span_log", "record_span", "record_instant",
    "merge_chrome_trace", "load_device_trace_events",
    "RequestTracer", "NullRequestTracer", "NULL_TRACER",
    "resolve_tracer", "LatencyReservoir", "validate_span_chain",
    "fleet_trace",
    "SignalWindow", "EngineCapacityMonitor", "CapacityConfig",
    "CapacityPlanner", "FleetCapacityMonitor",
    "resolve_capacity_monitor", "CAPACITY_ACTIONS",
]
