"""Per-step training telemetry: wall time, throughput, MFU, HBM, and a
NaN/Inf loss sentinel.

Roofline-style efficiency accounting (Tensor Processing Primitives,
arXiv:2104.05755) applied per step: MFU = achieved FLOP/s over the
chip's peak, with the FLOPs numerator taken from the COMPILED step's
``cost_analysis()`` (what XLA will actually execute — recompute,
fusions and collectives included) rather than an analytic 6ND guess.
HBM comes from the compiled module's ``memory_analysis()`` (static) and
the live device memory stats (:mod:`paddle_tpu.device`, sampled every
``hbm_sample_interval`` steps — the CPU fallback walks live arrays, so
per-step sampling would not be free).

The NaN/Inf sentinel is the reference's ``FLAGS_check_nan_inf``
equivalent: opt in with env ``PADDLE_TPU_CHECK_NAN_INF=1`` (or
``check_nan_inf=True``) and a non-finite loss raises
``FloatingPointError`` after bumping ``train_nonfinite_loss_total`` —
fail the job at the poisoned step instead of training garbage for hours.
"""
from __future__ import annotations

import math
import os
import time
from typing import Any, Dict, Optional

from .metrics import MetricsRegistry, default_registry
from .trace_merge import span_log

__all__ = ["StepTelemetry", "device_peak_flops", "PEAK_FLOPS_BY_KIND",
           "CHECK_NAN_ENV", "PEAK_FLOPS_ENV"]

CHECK_NAN_ENV = "PADDLE_TPU_CHECK_NAN_INF"
PEAK_FLOPS_ENV = "PADDLE_TPU_PEAK_FLOPS"

# bf16 (fp32 for pre-v4) dense peak FLOP/s per chip by device_kind
# prefix — the MFU denominator (same table bench.py reports against)
PEAK_FLOPS_BY_KIND = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> Optional[float]:
    """Per-chip peak FLOP/s: the ``PADDLE_TPU_PEAK_FLOPS`` env override
    if set, else the device_kind table; None when unknown (XLA CPU) —
    MFU is then reported as 0 rather than a made-up number."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        if device is None:
            device = jax.devices()[0]
    except Exception:                                 # noqa: BLE001
        return None
    kind = getattr(device, "device_kind", "") or ""
    # longest prefix first: "TPU v5 lite" must not match the "TPU v5"
    # (v5p) row
    for name in sorted(PEAK_FLOPS_BY_KIND, key=len, reverse=True):
        if kind.startswith(name):
            return PEAK_FLOPS_BY_KIND[name]
    return None


def _truthy_env(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "1", "true", "yes", "on")


class StepTelemetry:
    """Records one training step's telemetry into the metrics registry.

    Usage (what ``Engine.fit`` does)::

        tel = StepTelemetry()
        tel.attach_train_step(step, *sample_batch)   # FLOPs/HBM, once
        ...
        t0 = time.perf_counter()
        loss = step(*batch); loss_val = float(loss)  # host fetch
        tel.on_step(time.perf_counter() - t0, loss=loss_val,
                    examples=bs, tokens=bs * seq)
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 peak_flops: Optional[float] = None,
                 check_nan_inf: Optional[bool] = None,
                 hbm_sample_interval: int = 10,
                 span_markers: bool = True):
        r = registry or default_registry()
        self.registry = r
        self._steps = r.counter(
            "train_steps_total", "optimizer steps applied")
        self._duration = r.histogram(
            "train_step_duration_seconds",
            "wall time per fused train step (dispatch -> loss fetch)")
        self._examples_rate = r.gauge(
            "train_examples_per_second", "examples/s over the last step")
        self._tokens_rate = r.gauge(
            "train_tokens_per_second", "tokens/s over the last step")
        self._mfu = r.gauge(
            "train_mfu_ratio",
            "achieved FLOP/s over peak; FLOPs from the compiled step's "
            "cost_analysis (0 when peak or FLOPs are unknown)")
        self._loss = r.gauge("train_loss", "last step's loss")
        self._nonfinite = r.counter(
            "train_nonfinite_loss_total",
            "steps whose loss came back NaN/Inf "
            "(PADDLE_TPU_CHECK_NAN_INF sentinel)")
        self._flops_gauge = r.gauge(
            "train_step_flops",
            "FLOPs per compiled train step (cost_analysis)")
        self._temp_bytes = r.gauge(
            "train_step_temp_hbm_bytes",
            "compiled step's XLA temp allocation (memory_analysis)")
        self._hbm_in_use = r.gauge(
            "hbm_in_use_bytes", "live device memory at last sample")
        self._hbm_peak = r.gauge(
            "hbm_peak_bytes", "peak device memory at last sample")

        self.flops_per_step: Optional[float] = None
        self.peak_flops = peak_flops if peak_flops is not None \
            else device_peak_flops()
        self.check_nan_inf = _truthy_env(CHECK_NAN_ENV) \
            if check_nan_inf is None else bool(check_nan_inf)
        self.hbm_sample_interval = max(1, int(hbm_sample_interval))
        self.span_markers = bool(span_markers)
        self._n = 0

    # -- FLOPs / HBM source ---------------------------------------------------
    def set_flops_per_step(self, flops: Optional[float]):
        if flops:
            self.flops_per_step = float(flops)
            self._flops_gauge.set(float(flops))

    def attach_train_step(self, train_step, *batch) -> Dict[str, Any]:
        """Pull FLOPs + static memory sizes from the compiled step
        (``TrainStep.compiled_stats`` — AOT lower/compile, cached on the
        step).  One extra compile; returns the stats dict."""
        stats = train_step.compiled_stats(*batch)
        self.set_flops_per_step(stats.get("flops"))
        temp = stats.get("temp_bytes")
        if temp:
            self._temp_bytes.set(float(temp))
        return stats

    def sample_hbm(self):
        """Record live/peak device memory gauges now (device stats on
        TPU, the live-array fallback on CPU — never raises)."""
        try:
            from .. import device as _device
            self._hbm_in_use.set(float(_device.memory_allocated()))
            self._hbm_peak.set(float(_device.max_memory_allocated()))
        except Exception:                             # noqa: BLE001
            pass

    # -- per-step record ------------------------------------------------------
    def on_step(self, duration_s: float, loss: Optional[float] = None,
                examples: Optional[int] = None,
                tokens: Optional[int] = None,
                step_index: Optional[int] = None,
                warmup: bool = False):
        """Record one completed step; ``duration_s`` must span dispatch
        through the loss host-fetch (the real device barrier).  Raises
        ``FloatingPointError`` on a non-finite loss when the NaN/Inf
        sentinel is enabled.

        ``warmup=True`` marks a step whose wall time includes jit
        trace+compile (the first call of a fresh step): it is counted
        and loss-checked, but excluded from the duration histogram and
        the rate/MFU gauges so one multi-second compile doesn't skew
        the steady-state statistics forever."""
        self._n += 1
        dt = max(float(duration_s), 1e-9)
        self._steps.inc()
        if not warmup:
            self._duration.observe(dt)
            if examples:
                self._examples_rate.set(examples / dt)
            if tokens:
                self._tokens_rate.set(tokens / dt)
        if not warmup and self.flops_per_step and self.peak_flops:
            # cost_analysis FLOPs are PER-DEVICE (XLA divides sharded
            # work by the mesh size — verified: dp=8 reports 1/8 the
            # unsharded count), so per-chip peak is the denominator;
            # multiplying by device_count would under-report dp=8 by 8x
            self._mfu.set(self.flops_per_step / dt / self.peak_flops)
        if loss is not None:
            self._loss.set(float(loss))
            if not math.isfinite(float(loss)):
                self._nonfinite.inc()
                if self.check_nan_inf:
                    raise FloatingPointError(
                        f"non-finite loss {loss!r} at telemetry step "
                        f"{self._n} ({CHECK_NAN_ENV} sentinel); "
                        f"checkpoint + restart from the last finite "
                        f"state")
        if self._n % self.hbm_sample_interval == 0:
            self.sample_hbm()
        if self.span_markers:
            now = time.perf_counter()
            span_log.record("train_step", now - dt, now, cat="train",
                            step=int(step_index if step_index is not None
                                     else self._n))
