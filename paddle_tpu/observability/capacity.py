"""Fleet capacity & efficiency plane (round 20): windowed signals,
serving-step MFU, autoscaler-grade recommendations.

The load/SLO planes of rounds 9-16 publish POINT-IN-TIME snapshots
(``load_score`` reads one payload, the SLO counters are cumulative) —
an autoscaler acting on a snapshot flaps: one busy round reads as
"scale up", one idle round as "scale down".  This module turns those
same counters and gauges into DECISION-GRADE signals, pure host math
on the payloads the router already scrapes (zero new compiled
modules, zero extra endpoint traffic):

**Windowed signals.**  :class:`SignalWindow` is a bounded, thread-safe
ring of ``(perf_counter, value)`` samples computing O(1) rolling rates
(for monotone counters: tokens/s, admission rate, preempt/requeue
rate, host-tier spill+restore pressure), signed derivatives (for
gauges: queue-depth growth, prefix-hit-rate drift) and a time-decayed
EWMA (saturation smoothing).  An :class:`EngineCapacityMonitor` feeds
one window set per engine from ``engine.health_payload()`` — sampled
once per router step off the probe-refreshed payload, so monitoring
adds no scrapes.

**Serving-step device efficiency.**  The serving steps have had
``aot_lower()`` + cached compile artifacts since the round-17/18
plumbing, but only the TRAIN path published MFU.
``ContinuousBatchingEngine.efficiency_stats(compute=True)`` pulls
``cost_analysis()`` off the cached compiled serving step (the same
lazy one-extra-compile contract as ``TrainStep.compiled_stats``,
behind the same ``PADDLE_TPU_MFU_COST_ANALYSIS`` opt-out) and this
module folds it with the windowed tokens/s into per-engine gauges:
``serving_step_mfu`` (= tokens/s x flops/token / peak),
``serving_hbm_bytes_per_token`` and ``serving_model_flops_per_token``.
The peak-FLOPs denominator is the ONE round-9 table
(:func:`~paddle_tpu.observability.telemetry.device_peak_flops` — bench
and train telemetry already share it; this module imports it rather
than growing a third drifting copy).  Provenance note (BASELINE round
17): the numbers come from the compiled XLA step — on CPU that is the
XLA reference attention, NOT the interpret-mode Pallas kernel, whose
cost accounting differs (see BENCH_KERNEL_r17.json's honesty notes).

**Capacity planning.**  :class:`CapacityPlanner` folds the per-engine
signals into a fleet rollup and an advisory action —
``scale_up`` / ``scale_down`` / ``rebalance`` / ``steady`` — with
HYSTERESIS bands (enter scale_up above ``high_watermark``, leave only
below ``high_clear``; mirrored low bands for scale_down) and a
MINIMUM DWELL (a new candidate must persist ``min_dwell`` consecutive
evaluations before the committed recommendation changes), so boundary
dithering never flaps the recommendation.  The committed plan surfaces
in ``ServingRouter.capacity_plan()``,
``health_payload()["capacity"]`` (and therefore ``/healthz``), and
the ``router_capacity_*`` metrics.  ROADMAP item 5's actuation PR
(admit/drain engines, live resharding) consumes these signals; this
module deliberately stops at the recommendation.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional

from .metrics import MetricsRegistry, default_registry
# the ONE peak-FLOPs table (round 9) — imported, never copied: bench.py
# and StepTelemetry resolve peaks through these same symbols, and a
# regression test asserts the identity
from .telemetry import PEAK_FLOPS_BY_KIND, device_peak_flops

__all__ = ["SignalWindow", "EngineCapacityMonitor", "CapacityConfig",
           "CapacityPlanner", "FleetCapacityMonitor",
           "resolve_capacity_monitor", "CAPACITY_ACTIONS",
           "MFU_COST_ANALYSIS_ENV"]

# same opt-out the round-9 train MFU probe honors (tests/conftest.py
# sets it to 0 so the tier-1 budget never pays serving-step compiles)
MFU_COST_ANALYSIS_ENV = "PADDLE_TPU_MFU_COST_ANALYSIS"

CAPACITY_ACTIONS = ("scale_up", "scale_down", "rebalance", "steady")


def _cost_analysis_enabled() -> bool:
    return os.environ.get(MFU_COST_ANALYSIS_ENV, "1") != "0"


class SignalWindow:
    """Bounded thread-safe ring of ``(t, value)`` samples on the shared
    ``perf_counter`` clock, with O(1) windowed statistics.

    One window holds ONE signal.  ``rate()`` reads the value as a
    monotone counter (delta value over the window span, clamped at 0 so
    a counter reset — engine restart — reads as quiescence, not a
    negative rate); ``derivative()`` reads it as a gauge (signed slope:
    queue growth, hit-rate drift); ``ewma()`` is a time-decayed
    exponential mean (half-life in seconds, so irregular sampling
    periods weight correctly).  All methods are safe under concurrent
    writers: one lock guards the ring and the EWMA state, and every
    statistic is computed from a single locked read.
    """

    def __init__(self, maxlen: int = 128, halflife_s: float = 5.0):
        if maxlen < 2:
            raise ValueError("SignalWindow maxlen must be >= 2 (rates "
                             "need two samples); got %r" % (maxlen,))
        self.maxlen = int(maxlen)
        self.halflife_s = float(halflife_s)
        self._lock = threading.Lock()
        self._buf: "deque[tuple]" = deque(maxlen=self.maxlen)
        self._ewma: Optional[float] = None
        self._ewma_t: float = 0.0

    def add(self, value, t: Optional[float] = None) -> None:
        t = time.perf_counter() if t is None else float(t)
        v = float(value)
        with self._lock:
            if self._ewma is None:
                self._ewma = v
            else:
                dt = t - self._ewma_t
                if dt > 0 and self.halflife_s > 0:
                    alpha = 1.0 - 0.5 ** (dt / self.halflife_s)
                else:
                    # zero/negative dt (same-tick samples, clock
                    # weirdness): a plain step keeps the EWMA bounded
                    alpha = 0.5
                self._ewma += alpha * (v - self._ewma)
            self._ewma_t = t
            self._buf.append((t, v))

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def last(self) -> Optional[float]:
        with self._lock:
            return self._buf[-1][1] if self._buf else None

    def span(self) -> float:
        """Seconds covered by the window (0 with < 2 samples)."""
        with self._lock:
            if len(self._buf) < 2:
                return 0.0
            return self._buf[-1][0] - self._buf[0][0]

    def _slope(self) -> float:
        # callers hold no lock; one locked snapshot of the endpoints
        with self._lock:
            if len(self._buf) < 2:
                return 0.0
            t0, v0 = self._buf[0]
            t1, v1 = self._buf[-1]
        dt = t1 - t0
        if dt <= 1e-9:
            return 0.0
        return (v1 - v0) / dt

    def rate(self) -> float:
        """Counter reading: windowed increments per second, >= 0."""
        return max(0.0, self._slope())

    def derivative(self) -> float:
        """Gauge reading: signed value change per second."""
        return self._slope()

    def ewma(self) -> Optional[float]:
        with self._lock:
            return self._ewma

    def mean(self) -> Optional[float]:
        with self._lock:
            if not self._buf:
                return None
            return sum(v for _, v in self._buf) / len(self._buf)


def _payload_counter(payload: Dict, name: str) -> float:
    try:
        return float(payload.get("counters", {}).get(name, 0) or 0)
    except (TypeError, ValueError):
        return 0.0


def saturation_of(payload: Dict) -> float:
    """Instantaneous saturation of one engine payload in [0, 1]: the
    max of slot pressure ((occupancy + waiting) / slots, capped) and
    KV-page utilization — an engine is saturated when EITHER axis is
    exhausted (a full pool stalls admission just as surely as full
    slots).  Pages the prefix cache could reclaim right now
    (``evictable_pages``) count as free: a cache-warm IDLE engine is
    headroom, not saturation — unlike ``load_score``, which
    deliberately prefers engines with genuinely free pages for
    placement.  Missing fields read unloaded."""
    try:
        slots = max(1, int(payload.get("slots", 1)))
        slot_term = (float(payload.get("occupancy", 0))
                     + float(payload.get("waiting", 0))) / slots
        total = max(1, int(payload.get("total_pages", 1)))
        free = (float(payload.get("free_pages", total))
                + float(payload.get("evictable_pages", 0)))
        kv_term = 1.0 - min(free, total) / total
    except (TypeError, ValueError):
        return 0.0
    return min(1.0, max(slot_term, kv_term, 0.0))


class EngineCapacityMonitor:
    """One engine's windowed signal set, fed from its health payload.

    ``sample(payload)`` is the ONLY per-step cost (a handful of locked
    deque appends); every derived statistic is computed on read.
    ``engine`` (optional, in-process pools only) is the efficiency
    source — :meth:`efficiency` pulls the cached serving-step
    ``cost_analysis`` numbers off it; remote handles instead surface
    them through the payload's ``efficiency`` block when the remote
    process computed them.
    """

    def __init__(self, engine_id: int, engine=None,
                 maxlen: int = 128, halflife_s: float = 5.0):
        self.engine_id = int(engine_id)
        self.engine = engine
        # flipped by the fleet monitor as the router's health view
        # changes: an unhealthy engine's windows stop updating, so its
        # last (often saturated) EWMA must not pin the fleet rollup —
        # the monitor is kept so a recovered engine resumes its history
        self.healthy = True
        mk = lambda: SignalWindow(maxlen, halflife_s)   # noqa: E731
        self.w_tokens = mk()          # counter: tokens generated
        self.w_admitted = mk()        # counter: requests admitted
        self.w_preempts = mk()        # counter: preempt/requeue pulls
        self.w_host_tier = mk()       # counter: spills + restores
        self.w_queue = mk()           # gauge: waiting depth
        self.w_saturation = mk()      # gauge: instantaneous saturation
        self.w_hit_rate = mk()        # gauge: cumulative prefix hit rate
        self.last_payload: Dict = {}

    def sample(self, payload: Dict, t: Optional[float] = None) -> None:
        t = time.perf_counter() if t is None else float(t)
        self.last_payload = payload
        self.w_tokens.add(_payload_counter(payload, "tokens_generated"), t)
        self.w_admitted.add(
            _payload_counter(payload, "requests_admitted"), t)
        self.w_preempts.add(_payload_counter(payload, "preempts"), t)
        self.w_host_tier.add(
            _payload_counter(payload, "host_tier_spills")
            + _payload_counter(payload, "host_tier_restores"), t)
        self.w_queue.add(float(payload.get("waiting", 0) or 0), t)
        self.w_saturation.add(saturation_of(payload), t)
        lookups = _payload_counter(payload, "prefix_lookups")
        hits = _payload_counter(payload, "prefix_hits")
        if lookups > 0:
            self.w_hit_rate.add(hits / lookups, t)

    def signals(self) -> Dict[str, float]:
        """The derived per-engine signal block — plain floats only (it
        rides ``/healthz`` JSON and actuators compare on it), so the
        prefix-hit fields are OMITTED until a lookup has been observed
        (an engine without a prefix cache never grows them) rather
        than published as None."""
        sat = self.w_saturation.ewma()
        out = {
            "tokens_per_s": self.w_tokens.rate(),
            "admissions_per_s": self.w_admitted.rate(),
            "preempts_per_s": self.w_preempts.rate(),
            "host_tier_per_s": self.w_host_tier.rate(),
            "queue_depth": float(self.w_queue.last() or 0.0),
            "queue_growth_per_s": self.w_queue.derivative(),
            "saturation": float(sat if sat is not None else 0.0),
            "headroom": float(1.0 - (sat if sat is not None else 0.0)),
            "samples": len(self.w_saturation),
        }
        hit = self.w_hit_rate.last()
        if hit is not None:
            out["prefix_hit_rate"] = float(hit)
            out["prefix_hit_rate_drift"] = self.w_hit_rate.derivative()
        return out

    # ---- serving-step device efficiency ---------------------------------
    def efficiency(self, compute: bool = False,
                   peak_flops: Optional[float] = None
                   ) -> Optional[Dict[str, float]]:
        """Per-engine device-efficiency block, or None when no
        ``cost_analysis`` numbers are available.  ``compute=True``
        triggers the engine's lazy one-extra-compile probe (env-gated,
        cached on the engine) — never pass it from a liveness path.
        MFU folds the WINDOWED tokens/s with the static flops/token:
        achieved FLOP/s over the per-chip peak (0 when the peak is
        unknown — the round-9 convention: report 0, never invent a
        denominator)."""
        stats = None
        if self.engine is not None:
            fn = getattr(self.engine, "efficiency_stats", None)
            if fn is not None:
                stats = fn(compute=compute)
        if stats is None:
            stats = self.last_payload.get("efficiency")
        if not isinstance(stats, dict) or not stats.get("flops_per_token"):
            return None
        peak = peak_flops if peak_flops is not None else \
            device_peak_flops()
        tps = self.w_tokens.rate()
        flops_tok = float(stats["flops_per_token"])
        out = {
            "flops_per_token": flops_tok,
            "hbm_bytes_per_token": float(
                stats.get("hbm_bytes_per_token", 0.0)),
            "tokens_per_s": tps,
            "mfu": (tps * flops_tok / peak) if peak else 0.0,
            "peak_flops": float(peak) if peak else 0.0,
            "source": stats.get("source", "cost_analysis"),
        }
        return out


@dataclass
class CapacityConfig:
    """Planner bands + windowing (the DECLARED hysteresis the bench
    gate cites).  Saturations are fleet slot-weighted EWMAs in [0, 1].

    - enter ``scale_up`` at fleet saturation >= ``high_watermark`` (or
      a growing backlog while above ``high_clear``); leave only once
      saturation < ``high_clear``;
    - enter ``scale_down`` at saturation <= ``low_watermark`` with an
      empty backlog; leave once saturation > ``low_clear``;
    - ``rebalance`` when the per-engine saturation spread exceeds
      ``imbalance_threshold`` in the mid-band;
    - a NEW candidate must persist ``min_dwell`` consecutive
      evaluations before the committed recommendation changes;
    - ``sample_every``: the monitor samples + ticks every Nth router
      step (default 4).  Capacity decisions live on second-scale
      horizons (the EWMA half-life), so per-step resolution buys
      nothing — decimation is what keeps the monitor's overhead in
      the noise on sub-ms engine steps.  Tests that count ticks pass
      ``sample_every=1``.
    """
    high_watermark: float = 0.85
    high_clear: float = 0.70
    low_watermark: float = 0.25
    low_clear: float = 0.40
    imbalance_threshold: float = 0.45
    min_dwell: int = 3
    window: int = 128
    halflife_s: float = 5.0
    sample_every: int = 4

    def __post_init__(self):
        if not (0.0 <= self.low_watermark <= self.low_clear
                <= self.high_clear <= self.high_watermark <= 1.0):
            raise ValueError(
                "capacity bands must satisfy 0 <= low_watermark <= "
                "low_clear <= high_clear <= high_watermark <= 1; got "
                "%r" % (self,))
        if self.min_dwell < 1:
            raise ValueError("min_dwell must be >= 1; got %r"
                             % (self.min_dwell,))
        if self.sample_every < 1:
            raise ValueError("sample_every must be >= 1; got %r"
                             % (self.sample_every,))


class CapacityPlanner:
    """The hysteresis + dwell state machine over fleet signals.

    Pure host state, deterministic given the evaluation sequence —
    tests drive :meth:`evaluate` directly with synthetic signal dicts.
    ``actions`` records every COMMITTED transition (what the bench's
    zero-flap gate counts); ``evaluations`` counts calls.
    """

    def __init__(self, config: Optional[CapacityConfig] = None):
        self.config = config or CapacityConfig()
        self.action = "steady"
        self.evaluations = 0
        self.since = 0                # evaluations since last commit
        self._cand = "steady"
        self._cand_streak = 0
        self.actions: List[str] = []  # committed transitions, in order

    # ---- candidate ------------------------------------------------------
    def _candidate(self, fleet: Dict[str, float]) -> str:
        c = self.config
        sat = float(fleet.get("saturation", 0.0))
        pending = float(fleet.get("pending", 0.0))
        growth = float(fleet.get("queue_growth_per_s", 0.0))
        spread = float(fleet.get("saturation_spread", 0.0))
        n_eng = int(fleet.get("engines", 1))
        # hysteresis: the current recommendation defends its band
        if self.action == "scale_up" and sat >= c.high_clear:
            return "scale_up"
        if self.action == "scale_down" and sat <= c.low_clear \
                and pending == 0:
            return "scale_down"
        if sat >= c.high_watermark or (pending > 0 and growth > 0
                                       and sat >= c.high_clear):
            return "scale_up"
        if sat <= c.low_watermark and pending == 0 and growth <= 0:
            return "scale_down"
        if n_eng >= 2 and spread >= c.imbalance_threshold:
            return "rebalance"
        return "steady"

    def evaluate(self, fleet: Dict[str, float]) -> str:
        """One planner tick: fold the fleet signal dict into the
        committed recommendation (minimum-dwell: a candidate that has
        not persisted ``min_dwell`` consecutive ticks leaves the
        committed action unchanged)."""
        self.evaluations += 1
        self.since += 1
        cand = self._candidate(fleet)
        if cand == self.action:
            self._cand = cand
            self._cand_streak = 0
            return self.action
        if cand == self._cand:
            self._cand_streak += 1
        else:
            self._cand = cand
            self._cand_streak = 1
        if self._cand_streak >= self.config.min_dwell:
            self.action = cand
            self.actions.append(cand)
            self.since = 0
            self._cand_streak = 0
        return self.action


class FleetCapacityMonitor:
    """Per-engine windows + the planner + the metric surface — what a
    ``ServingRouter(capacity=...)`` owns.  ``observe_router`` is the
    one per-step hook (samples the probe-refreshed payloads, ticks the
    planner, refreshes gauges); ``capacity_plan`` is the read API."""

    def __init__(self, config: Optional[CapacityConfig] = None,
                 registry: Optional[MetricsRegistry] = None,
                 peak_flops: Optional[float] = None):
        self.config = config or CapacityConfig()
        self.planner = CapacityPlanner(self.config)
        # guards the monitor MAP (inserted into by the router's step
        # thread, iterated by /healthz scrape threads reading
        # capacity_plan through router.health_payload — an unlocked
        # insert-during-iteration raises and silently degrades the
        # scrape to the bare body); the windows below it carry their
        # own locks
        self._lock = threading.Lock()
        self.engines: Dict[int, EngineCapacityMonitor] = {}
        self.w_pending = SignalWindow(self.config.window,
                                      self.config.halflife_s)
        # resolved once: the env override / device-kind table (None on
        # CPU — MFU then publishes 0, the round-9 convention)
        self.peak_flops = peak_flops if peak_flops is not None \
            else device_peak_flops()
        self._plan: Optional[Dict] = None
        self._fleet: Optional[Dict] = None
        self._published_action: Optional[str] = None
        self._observations = 0
        r = registry or default_registry()
        self._m_reco = r.gauge(
            "router_capacity_recommendation",
            "one-hot committed capacity recommendation (hysteresis + "
            "minimum-dwell applied): the advisory action ROADMAP item "
            "5's actuator consumes", labels=("action",))
        self._reco_children = {
            a: self._m_reco.labels(action=a) for a in CAPACITY_ACTIONS}
        self._m_transitions = r.counter(
            "router_capacity_transitions_total",
            "committed recommendation changes by destination action — "
            "a flap shows up here as a reversal inside one load "
            "regime, which the hysteresis bands + min_dwell forbid",
            labels=("action",))
        self._trans_children = {
            a: self._m_transitions.labels(action=a)
            for a in CAPACITY_ACTIONS}
        self._n_transitions_published = 0
        self._m_sat = r.gauge(
            "router_capacity_saturation_ratio",
            "fleet saturation: slot-weighted EWMA over per-engine "
            "max(slot pressure, KV-page utilization), in [0, 1]")
        self._m_headroom = r.gauge(
            "router_capacity_headroom_ratio",
            "1 - fleet saturation: how much of the current fleet is "
            "still spare before the scale_up band")
        self._m_tps = r.gauge(
            "router_capacity_tokens_per_second",
            "fleet-wide windowed generation rate (sum of per-engine "
            "rolling rates)")
        self._m_mfu = r.gauge(
            "serving_step_mfu",
            "per-engine serving MFU: windowed tokens/s x compiled-step "
            "flops/token over per-chip peak (cost_analysis of the "
            "cached AOT serving step; 0 when the peak is unknown)",
            labels=("engine",))
        self._m_hbm_tok = r.gauge(
            "serving_hbm_bytes_per_token",
            "compiled serving step bytes-accessed per packed budget "
            "token (cost_analysis; pool operands included)",
            labels=("engine",))
        self._m_flops_tok = r.gauge(
            "serving_model_flops_per_token",
            "compiled serving step FLOPs per packed budget token "
            "(cost_analysis of the XLA module actually executed)",
            labels=("engine",))

    # ---- sampling -------------------------------------------------------
    def monitor_for(self, engine_id: int,
                    engine=None) -> EngineCapacityMonitor:
        with self._lock:
            m = self.engines.get(int(engine_id))
            if m is None:
                m = self.engines[int(engine_id)] = EngineCapacityMonitor(
                    engine_id, engine=engine,
                    maxlen=self.config.window,
                    halflife_s=self.config.halflife_s)
            if engine is not None and m.engine is None:
                m.engine = engine
            return m

    def _monitors(self) -> List[EngineCapacityMonitor]:
        """One locked snapshot of the monitor map for iteration (the
        step thread may be admitting a late engine concurrently)."""
        with self._lock:
            return list(self.engines.values())

    def drop_engine(self, engine_id: int) -> None:
        """Forget an engine retired from the pool (the router's
        ``remove_engine`` calls this): its frozen windows leave the
        rollup entirely — unlike a LOST engine, whose monitor stays
        for diagnosis with ``healthy=False``."""
        with self._lock:
            self.engines.pop(int(engine_id), None)

    def observe_router(self, router, t: Optional[float] = None) -> str:
        """One router step's sampling + LIGHT planner tick.  Reads
        each healthy handle's ``last_payload`` (refreshed by the
        router's own probe pass — no extra scrapes) and the router's
        pending depth; returns the committed action.  This is the
        per-step hot path, so it deliberately stops at the rollup +
        the scalar gauges — the full plan dict (per-engine signal
        blocks, efficiency gauges) is built lazily on
        :meth:`capacity_plan` / :meth:`evaluate` reads, and the whole
        body runs only every ``sample_every``-th call — the window
        timestamps are real, so decimation changes resolution, not
        the rates."""
        self._observations += 1
        if (self._observations - 1) % self.config.sample_every:
            return self.planner.action
        t = time.perf_counter() if t is None else float(t)
        for h in router.handles.values():
            if h.healthy and h.last_payload:
                # lock-free fast path: dict.get is GIL-atomic, and
                # monitors are only ever INSERTED (under the lock in
                # monitor_for), never removed — the lock matters for
                # insert-during-iteration, not for this lookup
                m = self.engines.get(h.engine_id)
                if m is None:
                    eng = (None if getattr(h, "health_url", None)
                           else h.engine)
                    m = self.monitor_for(h.engine_id, engine=eng)
                m.healthy = True
                m.sample(h.last_payload, t)
            else:
                # a lost engine's windows freeze at their last (often
                # saturated) values — flag its monitor out of the
                # rollup or the planner would chase a ghost forever
                with self._lock:
                    m = self.engines.get(h.engine_id)
                if m is not None:
                    m.healthy = False
        self.w_pending.add(len(router.pending), t)
        return self.tick()

    # ---- rollup + plan --------------------------------------------------
    def fleet_signals(self) -> Dict[str, float]:
        """The fleet rollup, off DIRECT window reads (a few locked
        endpoint reads per engine — the per-step budget; the verbose
        per-engine dicts are plan-time only)."""
        sat_sum, w_sum, tps = 0.0, 0, 0.0
        spread_lo, spread_hi = None, None
        monitors = [m for m in self._monitors() if m.healthy]
        for m in monitors:
            s = m.w_saturation.ewma()
            if s is None:
                continue
            slots = max(1, int(m.last_payload.get("slots", 1)))
            sat_sum += s * slots
            w_sum += slots
            tps += m.w_tokens.rate()
            spread_lo = s if spread_lo is None else min(spread_lo, s)
            spread_hi = s if spread_hi is None else max(spread_hi, s)
        sat = (sat_sum / w_sum) if w_sum else 0.0
        return {
            "saturation": float(sat),
            "headroom": float(1.0 - sat),
            "saturation_spread": float((spread_hi - spread_lo)
                                       if spread_hi is not None else 0.0),
            "tokens_per_s": float(tps),
            "pending": float(self.w_pending.last() or 0.0),
            "queue_growth_per_s": self.w_pending.derivative(),
            "engines": len(monitors),
        }

    def tick(self) -> str:
        """One light planner tick: rollup -> hysteresis/dwell ->
        gauges (one-hot recommendation only rewritten on an action
        CHANGE; the scalar gauges every 16th tick and on every plan
        read, the r16 scrape-exactness pattern).  Invalidates the
        cached plan."""
        fleet = self.fleet_signals()
        action = self.planner.evaluate(fleet)
        if action != self._published_action:
            for a, child in self._reco_children.items():
                child.set(1.0 if a == action else 0.0)
            self._published_action = action
        while self._n_transitions_published < len(self.planner.actions):
            a = self.planner.actions[self._n_transitions_published]
            self._trans_children[a].inc()
            self._n_transitions_published += 1
        if self.planner.evaluations % 16 == 1:
            self._publish_scalar_gauges(fleet)
        self._fleet = fleet
        self._plan = None
        return action

    def _publish_scalar_gauges(self, fleet: Dict) -> None:
        self._m_sat.set(fleet["saturation"])
        self._m_headroom.set(fleet["headroom"])
        self._m_tps.set(fleet["tokens_per_s"])

    def evaluate(self) -> Dict:
        """Full evaluation: one planner tick, then the complete plan
        dict (per-engine signal blocks + efficiency gauges)."""
        self.tick()
        return self.capacity_plan()

    def _build_plan(self) -> Dict:
        fleet = self._fleet if self._fleet is not None \
            else self.fleet_signals()
        # any plan read leaves the scrape exact (gauges are otherwise
        # refreshed every 16th tick)
        self._publish_scalar_gauges(fleet)
        action = self.planner.action
        engines = {}
        for m in self._monitors():
            eid = m.engine_id
            engines[str(eid)] = sig = m.signals()
            sig["healthy"] = m.healthy
            if not m.healthy:
                # frozen windows: keep the block for diagnosis, but
                # publish no rates-derived efficiency off it
                continue
            eff = m.efficiency(compute=False,
                               peak_flops=self.peak_flops)
            if eff is not None:
                sig["efficiency"] = eff
                e = str(eid)
                self._m_mfu.labels(engine=e).set(eff["mfu"])
                self._m_hbm_tok.labels(engine=e).set(
                    eff["hbm_bytes_per_token"])
                self._m_flops_tok.labels(engine=e).set(
                    eff["flops_per_token"])
        return {
            "action": action,
            "since_evaluations": self.planner.since,
            "evaluations": self.planner.evaluations,
            "transitions": list(self.planner.actions),
            "fleet": fleet,
            "engines": engines,
            # round 25: the actuator's work order — concrete
            # (source, target) engine pairs ranked by saturation
            # spread, so a rebalance recommendation names exactly
            # which engines shed to which (no re-derivation)
            "rebalance_pairs": self.rebalance_pairs(),
            "bands": {
                "high_watermark": self.config.high_watermark,
                "high_clear": self.config.high_clear,
                "low_watermark": self.config.low_watermark,
                "low_clear": self.config.low_clear,
                "imbalance_threshold": self.config.imbalance_threshold,
                "min_dwell": self.config.min_dwell,
            },
        }

    def rebalance_pairs(self) -> List[Dict]:
        """Concrete rebalance work orders: the most-saturated healthy
        engine paired with the least-saturated, second-most with
        second-least, and so on — ranked by per-pair saturation
        spread, keeping only pairs whose spread is positive.  The
        elastic actuator moves pages/requests source -> target
        verbatim; ``/healthz["capacity"]["rebalance_pairs"]`` carries
        the same list."""
        sats = []
        for m in self._monitors():
            if not m.healthy:
                continue
            s = m.w_saturation.ewma()
            if s is not None:
                sats.append((float(s), int(m.engine_id)))
        if len(sats) < 2:
            return []
        sats.sort(key=lambda t: (-t[0], t[1]))
        pairs = []
        for i in range(len(sats) // 2):
            hi_s, hi_id = sats[i]
            lo_s, lo_id = sats[-1 - i]
            spread = hi_s - lo_s
            if spread <= 0.0:
                break
            pairs.append({"source_engine": hi_id,
                          "target_engine": lo_id,
                          "spread": round(spread, 6)})
        return pairs

    def capacity_plan(self) -> Dict:
        """The committed plan, built lazily off the last tick's
        rollup (a never-ticked monitor plans ``steady`` over whatever
        has been sampled; reads never advance the planner — dwell
        counts router steps, not scrapes)."""
        if self._plan is None:
            self._plan = self._build_plan()
        return self._plan

    def refresh_efficiency(self, compute: bool = True) -> Dict[str, Dict]:
        """Force the per-engine efficiency blocks (in-process engines
        only; ``compute=True`` triggers each engine's lazy env-gated
        cost_analysis probe).  Returns {engine_id: block} for engines
        that produced numbers; gauges refresh on the next evaluate."""
        out = {}
        for m in self._monitors():
            eff = m.efficiency(compute=compute,
                               peak_flops=self.peak_flops)
            if eff is not None:
                out[str(m.engine_id)] = eff
        return out


def resolve_capacity_monitor(capacity) -> Optional[FleetCapacityMonitor]:
    """The one ``capacity=`` knob parser (mirrors ``resolve_tracer``):
    None/False -> no monitoring (the router stays byte-identical to
    r19); True -> a default-config :class:`FleetCapacityMonitor`; a
    :class:`CapacityConfig` -> a monitor with those bands; a prebuilt
    monitor passes through."""
    if capacity is None or capacity is False:
        return None
    if capacity is True:
        return FleetCapacityMonitor()
    if isinstance(capacity, CapacityConfig):
        return FleetCapacityMonitor(capacity)
    if isinstance(capacity, FleetCapacityMonitor):
        return capacity
    raise ValueError(
        "capacity= must be None/False, True, a CapacityConfig, or a "
        "FleetCapacityMonitor; got %r" % (capacity,))
