"""Merged host + device Chrome traces.

The two-tier profiler (SURVEY §5.1) leaves three timeline sources lying
around: host ``RecordEvent`` spans (paddle_tpu.profiler), the runtime
span log this module keeps (step markers, checkpoint writes, comm
timeouts), and the device trace ``jax.profiler`` writes under its trace
dir — which on this jax build includes a ready-made chrome trace
(``plugins/profile/<run>/<host>.trace.json.gz``).  ``merge_chrome_trace``
folds all three into ONE chrome://tracing JSON so a single load shows
the train loop, the checkpoint writer and the XLA device activity
side by side.

Clock domains: host spans are ``time.perf_counter`` based, the device
trace has its own epoch; each source is shifted so its earliest event
sits at t=0 (alignment at trace start — sub-trace ordering is exact,
cross-trace skew is bounded by the capture window).
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import threading
import time
from typing import Dict, Iterable, List, Optional

__all__ = ["SpanLog", "span_log", "record_span", "record_instant",
           "load_device_trace_events", "merge_chrome_trace"]

_SPAN_LOG_CAP = 16384


class SpanLog:
    """Bounded in-memory log of runtime spans/instants (step markers,
    checkpoint writes, watchdog timeouts).  An append is one lock + one
    deque.append — cheap enough for per-step use; the cap drops the
    OLDEST entries so a week-long job keeps the recent window.  The
    append AND the eviction run under one lock: concurrent writers
    (train thread + checkpoint writer + watchdog) can never race the
    bound past ``maxlen`` or drop each other's fresh entries."""

    def __init__(self, maxlen: int = _SPAN_LOG_CAP):
        self._maxlen = max(1, int(maxlen))
        self._events: "collections.deque" = collections.deque()
        self._lock = threading.Lock()

    def _append(self, entry: tuple):
        with self._lock:
            self._events.append(entry)
            while len(self._events) > self._maxlen:
                self._events.popleft()

    def record(self, name: str, start: float, end: float,
               cat: str = "runtime", **args):
        """A completed span; start/end are time.perf_counter seconds."""
        self._append(("X", name, cat, start, end, args,
                      threading.get_ident()))

    def instant(self, name: str, ts: Optional[float] = None,
                cat: str = "runtime", **args):
        t = time.perf_counter() if ts is None else ts
        self._append(("i", name, cat, t, t, args,
                      threading.get_ident()))

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)


# process-wide log every wired subsystem appends to
span_log = SpanLog()


def record_span(name: str, start: float, end: float,
                cat: str = "runtime", **args):
    span_log.record(name, start, end, cat, **args)


def record_instant(name: str, ts: Optional[float] = None,
                   cat: str = "runtime", **args):
    span_log.instant(name, ts, cat, **args)


def _tid_map(idents: Iterable[int]) -> Dict[int, int]:
    """Stable small thread ids (chrome renders 15-digit pthread idents
    as separate unreadable lanes)."""
    return {ident: i for i, ident in enumerate(sorted(set(idents)))}


def load_device_trace_events(trace_dir: str) -> List[dict]:
    """traceEvents from the chrome trace(s) jax.profiler wrote under
    ``trace_dir`` (``**/*.trace.json[.gz]``); [] when the dir is missing
    or holds no trace — a device-less CPU/host-only run merges cleanly
    to host spans alone."""
    if not trace_dir or not os.path.isdir(trace_dir):
        return []
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(trace_dir, "**", "*.trace.json"),
                    recursive=True))
    events: List[dict] = []
    for path in paths:
        try:
            if path.endswith(".gz"):
                with gzip.open(path, "rt") as f:
                    data = json.load(f)
            else:
                with open(path) as f:
                    data = json.load(f)
        except (OSError, ValueError):
            continue
        evs = data.get("traceEvents", data) if isinstance(data, dict) \
            else data
        if isinstance(evs, list):
            events.extend(e for e in evs if isinstance(e, dict))
    return events


def _host_events_json(host_events, pid: int,
                      t0: Optional[float] = None) -> List[dict]:
    """paddle_tpu.profiler _HostEvent list -> chrome 'X' events +
    name metadata, normalized to t=0 at ``t0`` (defaults to the
    earliest span)."""
    if not host_events:
        return []
    if t0 is None:
        t0 = min(e.start for e in host_events)
    tids = _tid_map(e.tid for e in host_events)
    # spans FIRST, name metadata after: tools that peek at
    # traceEvents[0] (and the repo's own round-trip checks) see a real
    # 'X' span, and chrome accepts metadata at any position
    out = [{"name": e.name, "ph": "X", "pid": pid,
            "tid": tids[e.tid], "ts": (e.start - t0) * 1e6,
            "dur": (e.end - e.start) * 1e6,
            "cat": getattr(e, "event_type", "UserDefined")}
           for e in sorted(host_events, key=lambda e: e.start)]
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": "host (RecordEvent)"}})
    for ident, tid in tids.items():
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"host-thread-{tid}"}})
    return out


def _span_log_events_json(entries, pid: int,
                          t0: Optional[float] = None) -> List[dict]:
    if not entries:
        return []
    if t0 is None:
        t0 = min(e[3] for e in entries)
    tids = _tid_map(e[6] for e in entries)
    out = []
    for ph, name, cat, start, end, args, ident in entries:
        ev = {"name": name, "ph": ph, "pid": pid, "tid": tids[ident],
              "ts": (start - t0) * 1e6, "cat": cat}
        if ph == "X":
            ev["dur"] = (end - start) * 1e6
        else:
            ev["s"] = "t"          # thread-scoped instant
        if args:
            ev["args"] = {k: v for k, v in args.items()}
        out.append(ev)
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": "runtime (steps/ckpt/comm)"}})
    for ident, tid in tids.items():
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid,
                    "args": {"name": f"runtime-thread-{tid}"}})
    return out


def _device_events_json(events: List[dict], pid_base: int) -> List[dict]:
    """Re-base the device trace: pids offset so they never collide with
    the host groups, timestamps shifted to t=0 at the earliest event."""
    if not events:
        return []
    ts_vals = []
    for e in events:
        try:
            ts_vals.append(float(e["ts"]))
        except (KeyError, TypeError, ValueError):
            pass
    ts0 = min(ts_vals) if ts_vals else 0.0   # metadata-only trace: keep
    out = []
    for e in events:
        ev = dict(e)
        if "pid" in ev:
            try:
                ev["pid"] = pid_base + int(ev["pid"])
            except (TypeError, ValueError):
                ev["pid"] = pid_base
        else:
            ev["pid"] = pid_base
        try:
            ev["ts"] = float(ev["ts"]) - ts0
        except (KeyError, TypeError, ValueError):
            pass                             # no/odd ts: pass through
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            args = dict(ev.get("args") or {})
            args["name"] = f"device: {args.get('name', 'jax')}"
            ev["args"] = args
        out.append(ev)
    return out


def _extra_group_json(name: str, group_events: List[dict], pid: int,
                      t0: Optional[float]) -> List[dict]:
    """One caller-built track group (e.g. a request tracer's events):
    chrome dicts whose ``ts``/``dur`` are ABSOLUTE perf_counter seconds
    — this shifts them onto the shared t0 and scales to µs, assigns the
    group's pid, and appends its process_name metadata."""
    if not group_events:
        return []
    base = t0 or 0.0
    out = []
    for e in group_events:
        ev = dict(e)
        ev["pid"] = pid
        if "ts" in ev:
            ev["ts"] = (float(ev["ts"]) - base) * 1e6
        if "dur" in ev:
            ev["dur"] = float(ev["dur"]) * 1e6
        out.append(ev)
    out.append({"name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": name}})
    return out


def merge_chrome_trace(path: str, host_events=None,
                       runtime_events=None,
                       device_trace_dir: Optional[str] = None,
                       extra_groups=None) -> str:
    """Write one chrome://tracing JSON folding host RecordEvent spans,
    the runtime span log, and the device trace (if any) — the
    observability subsystem's single-timeline artifact.

    host_events: ``Profiler.events`` list (or None).
    runtime_events: a :class:`SpanLog` / its ``events()`` list; defaults
    to the process-wide :data:`span_log`.
    device_trace_dir: a ``jax.profiler`` trace dir; missing/empty dirs
    degrade to a host-only trace (the device-less CPU contract).
    extra_groups: ``[(process_name, chrome_event_dicts)]`` — additional
    track groups (one pid each) whose ``ts``/``dur`` are ABSOLUTE
    perf_counter seconds on the same clock as the host/runtime spans;
    the fleet request tracer (``request_trace.fleet_trace``) feeds the
    router's and every engine's request lanes through this.

    Output ordering is DETERMINISTIC: non-metadata events sort by
    ``(ts, pid, tid, name)`` — two spans sharing a timestamp always
    serialize in the same order, so traces diff cleanly across runs —
    with metadata after (the first traceEvent stays a real span).
    """
    if runtime_events is None:
        runtime_events = span_log
    if isinstance(runtime_events, SpanLog):
        runtime_events = runtime_events.events()
    pid = os.getpid()
    host_events = list(host_events or [])
    runtime_events = list(runtime_events or [])
    extra_groups = [(str(n), list(evs or []))
                    for n, evs in (extra_groups or [])]
    # host spans, runtime spans and extra groups share the perf_counter
    # clock: ONE t0 across all of them, or a checkpoint 45s into the
    # profile would render at t=0 next to the first host span
    starts = [e.start for e in host_events] \
        + [e[3] for e in runtime_events]
    for _name, evs in extra_groups:
        starts += [float(e["ts"]) for e in evs if "ts" in e]
    t0 = min(starts) if starts else None
    events: List[dict] = []
    events.extend(_host_events_json(host_events, pid, t0))
    events.extend(_span_log_events_json(runtime_events, pid + 1, t0))
    for i, (name, evs) in enumerate(extra_groups):
        events.extend(_extra_group_json(name, evs, pid + 2 + i, t0))
    events.extend(_device_events_json(
        load_device_trace_events(device_trace_dir), 1_000_000))
    # deterministic serialization: spans by (ts, pid, tid, name) —
    # ties included — then metadata (tools that peek at traceEvents[0]
    # must still see a real span)
    spans = [e for e in events if e.get("ph") != "M"]
    meta = [e for e in events if e.get("ph") == "M"]

    def _num(v):
        # device traces may carry non-numeric ids: numbers sort
        # numerically, anything else sorts after them as text — the
        # key never raises and stays deterministic either way
        try:
            return (0, float(v), "")
        except (TypeError, ValueError):
            return (1, 0.0, str(v))

    def _order(e):
        try:
            ts = float(e.get("ts", 0.0))
        except (TypeError, ValueError):
            ts = 0.0
        return (ts, _num(e.get("pid", 0)), _num(e.get("tid", 0)),
                str(e.get("name", "")))

    spans.sort(key=_order)
    out = {"displayTimeUnit": "ms", "traceEvents": spans + meta}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f)
    return path
