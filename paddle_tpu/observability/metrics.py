"""Unified runtime metrics registry.

SURVEY §5.1/§5.5: the reference ships a two-tier profiler but "no
Prometheus-style exporter in-repo" — production serving/training jobs
watch throughput, queue depths and checkpoint health through external
sidecars.  This module is the in-repo answer: a process-wide registry of
labeled ``Counter`` / ``Gauge`` / ``Histogram`` instruments that every
subsystem (Engine.fit, ContinuousBatchingEngine, CheckpointManager,
DataLoader, comm_watchdog) records into, scraped by the exporters in
:mod:`paddle_tpu.observability.exporters`.

Design constraints:

- **Hot-path cheap.**  Instruments sit inside the train/decode loops, so
  an increment is one dict lookup + one tiny per-child lock (never the
  registry lock); registration (``registry.counter(...)``) is idempotent
  so call sites can re-register on every construction without keeping
  module globals.
- **Fixed histogram buckets.**  Boundaries are frozen at registration
  (Prometheus semantics) — observation is a linear scan over ~a dozen
  floats, no allocation.
- **Naming contract** (enforced here and by
  ``tools/check_metric_names.py``): snake_case, counters end in
  ``_total``, durations in ``_seconds``, sizes in ``_bytes``.
"""
from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["MetricsRegistry", "Counter", "Gauge", "Histogram",
           "MetricError", "default_registry", "counter", "gauge",
           "histogram", "DEFAULT_BUCKETS"]

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# latency-shaped default (seconds): sub-ms dispatch up to multi-second
# compile/checkpoint stalls
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0)


class MetricError(ValueError):
    """Bad metric name / label schema / conflicting registration."""


def _check_name(name: str, kind: str):
    if not _NAME_RE.match(name or ""):
        raise MetricError(
            f"metric name {name!r} must be snake_case "
            f"([a-z][a-z0-9_]*)")
    if kind == "counter" and not name.endswith("_total"):
        raise MetricError(
            f"counter {name!r} must end in '_total' "
            f"(prometheus unit-suffix convention)")
    if kind != "counter" and name.endswith("_total"):
        raise MetricError(
            f"{kind} {name!r} must not end in '_total' (counters only)")


class _Child:
    """One (metric, label-values) time series."""

    __slots__ = ("labels", "_lock", "_value")

    def __init__(self, labels: Dict[str, str]):
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class _CounterChild(_Child):
    __slots__ = ()

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise MetricError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    __slots__ = ()

    def set(self, value: float):
        # locked like inc/dec: a concurrent set between inc's read and
        # write must not be overwritten by the stale read + amount
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)


class _HistogramChild:
    __slots__ = ("labels", "_lock", "_bounds", "_counts", "_sum",
                 "_count")

    def __init__(self, labels: Dict[str, str],
                 bounds: Sequence[float]):
        self.labels = labels
        self._lock = threading.Lock()
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)   # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float):
        v = float(value)
        i = bisect.bisect_left(self._bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def quantile(self, q: float) -> float:
        """Estimated q-quantile by linear interpolation over the fixed
        buckets (``histogram_quantile`` semantics): find the bucket the
        rank ``q * count`` falls in, interpolate linearly inside it.
        The +Inf bucket has no upper edge — mass there reports the
        highest finite boundary (the estimate saturates, it never
        invents values).  NaN when nothing was observed."""
        q = min(1.0, max(0.0, float(q)))
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return float("nan")
        rank = q * total
        acc = 0
        for i, c in enumerate(counts):
            prev, acc = acc, acc + c
            if c and acc >= rank:
                if i >= len(self._bounds):          # +Inf bucket
                    return float(self._bounds[-1])
                hi = float(self._bounds[i])
                lo = float(self._bounds[i - 1]) if i > 0 \
                    else min(0.0, hi)
                frac = min(1.0, max(0.0, (rank - prev) / c))
                return lo + (hi - lo) * frac
        return float(self._bounds[-1])

    # prometheus exposition is CUMULATIVE per bucket
    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self._counts:
            acc += c
            out.append(acc)
        return out

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def count(self) -> int:
        return self._count


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        _check_name(name, self.kind)
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise MetricError(f"bad label name {ln!r} on {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            self._default = self._make_child({})
            self._children[()] = self._default
        else:
            self._default = None

    def _make_child(self, labels: Dict[str, str]):
        raise NotImplementedError

    def labels(self, **labelvalues):
        """The child series for these label values (created on first
        use).  Label NAMES must match the registration exactly — a typo'd
        or extra label is a schema bug, not a new series."""
        if tuple(sorted(labelvalues)) != tuple(sorted(self.labelnames)):
            raise MetricError(
                f"{self.name}: labels() got {sorted(labelvalues)}, "
                f"declared labelnames are {sorted(self.labelnames)}")
        key = tuple(str(labelvalues[ln]) for ln in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = self._make_child(
                        dict(zip(self.labelnames, key)))
                    self._children[key] = child
        return child

    def children(self):
        return list(self._children.values())

    def _need_default(self):
        if self._default is None:
            raise MetricError(
                f"{self.name} has labels {self.labelnames}; call "
                f".labels(...) first")
        return self._default

    def _schema(self):
        return (self.kind, self.labelnames)


class Counter(_Metric):
    kind = "counter"

    def _make_child(self, labels):
        return _CounterChild(labels)

    def inc(self, amount: float = 1.0):
        self._need_default().inc(amount)

    @property
    def value(self) -> float:
        return self._need_default().value


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self, labels):
        return _GaugeChild(labels)

    def set(self, value: float):
        self._need_default().set(value)

    def inc(self, amount: float = 1.0):
        self._need_default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._need_default().dec(amount)

    @property
    def value(self) -> float:
        return self._need_default().value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames,
                 buckets: Optional[Sequence[float]] = None):
        bounds = tuple(float(b) for b in (buckets if buckets is not None
                                          else DEFAULT_BUCKETS))
        if not bounds or any(b2 <= b1 for b1, b2
                             in zip(bounds, bounds[1:])):
            raise MetricError(
                f"{name}: bucket boundaries must be strictly "
                f"increasing and non-empty, got {bounds}")
        if any(math.isinf(b) or math.isnan(b) for b in bounds):
            raise MetricError(
                f"{name}: +Inf bucket is implicit; boundaries must be "
                f"finite")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _make_child(self, labels):
        return _HistogramChild(labels, self.buckets)

    def observe(self, value: float):
        self._need_default().observe(value)

    def quantile(self, q: float) -> float:
        """Estimated q-quantile from the fixed buckets (linear
        interpolation inside the containing bucket; labeled metrics:
        ``.labels(...).quantile(q)``)."""
        return self._need_default().quantile(q)

    @property
    def sum(self) -> float:
        return self._need_default().sum

    @property
    def count(self) -> int:
        return self._need_default().count

    def _schema(self):
        return (self.kind, self.labelnames, self.buckets)


class MetricsRegistry:
    """Name -> metric map with idempotent get-or-create registration.

    Re-registering an identical (name, kind, labelnames[, buckets])
    schema returns the EXISTING metric — subsystems register at their
    construction sites, and two engines in one process share series.
    A conflicting schema under the same name raises ``MetricError``.
    """

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name, help, labels, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                candidate_schema = (cls.kind, tuple(labels)) + (
                    ((tuple(float(b) for b in kw["buckets"])
                      if kw.get("buckets") is not None
                      else DEFAULT_BUCKETS),)
                    if cls is Histogram else ())
                if existing._schema() != candidate_schema:
                    raise MetricError(
                        f"metric {name!r} already registered with a "
                        f"different schema {existing._schema()!r}")
                return existing
            metric = cls(name, help, tuple(labels), **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        return self._metrics.get(name)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def collect(self) -> Iterable[_Metric]:
        with self._lock:
            metrics = list(self._metrics.values())
        return sorted(metrics, key=lambda m: m.name)

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self):
        """Drop every metric (tests)."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able {name: {type, help, series:[{labels, ...}]}} — the
        payload of the JSON exporter and ``bench.py --emit-metrics``."""
        out = {}
        for m in self.collect():
            series = []
            for ch in m.children():
                entry = {"labels": dict(ch.labels)}
                if isinstance(ch, _HistogramChild):
                    entry.update({
                        "buckets": list(m.buckets),
                        "counts": list(ch._counts),
                        "sum": ch.sum, "count": ch.count})
                else:
                    entry["value"] = ch.value
                series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every built-in subsystem records into
    (and the exporters scrape by default)."""
    return _DEFAULT


def counter(name: str, help: str = "",
            labels: Sequence[str] = ()) -> Counter:
    return _DEFAULT.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Gauge:
    return _DEFAULT.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _DEFAULT.histogram(name, help, labels, buckets=buckets)
