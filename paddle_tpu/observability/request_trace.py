"""Fleet request tracing: per-request phase spans + SLO attainment.

Round 16.  The r9 observability layer is per-process — counters,
histograms and one runtime span log — so a request's lifecycle through
the r15 multi-engine router (pending-queue wait, affinity hold, the
route decision, dispatch, per-chunk prefill, first token, decode,
preempt/requeue hops onto other engines, finish) is invisible
end-to-end, and the SLO targets admission orders on (``ttft_target`` /
``tpot_target``) are never *measured* for attainment.  This module is
the signal plane ROADMAP item 5's autoscaler will consume:

- :class:`RequestTracer` — a bounded, thread-safe log of TYPED
  per-request phase events/spans keyed by request id.  Every engine and
  every router owns one by default (``tracer=False`` drops to the
  no-op :data:`NULL_TRACER` stub, the overhead-bench control arm).
  All records are host control flow on the shared ``perf_counter``
  clock: zero device work, zero new compiled modules.
- :func:`fleet_trace` — merges the router's spans and every pool
  engine's spans into ONE chrome://tracing JSON (extending the r9
  ``merge_chrome_trace``): the router and each engine render as
  separate track groups (pids), every request is one lane (tid), and a
  requeued request's spans CHAIN across engines via chrome flow
  events (``ph: "s"/"f"``) — the cross-engine hop is a drawn arrow,
  not an exercise in eyeballing timestamps.
- :func:`validate_span_chain` — the completeness contract the bench
  gates on: a dispatched request's router-side chain must be gap-free
  (enqueue -> dispatch -> ... -> finish, every requeue hop re-
  dispatched, pending/on-engine spans tiling submit..done with no
  temporal hole).
- :class:`LatencyReservoir` — bounded reservoir sample (Algorithm R,
  seeded: deterministic) backing the router's p50/p95/p99 TTFT/TPOT
  digests in ``health_payload()`` / ``/healthz``; the Prometheus twin
  is the ``router_latency_quantile_seconds{kind,q}`` gauge family.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["RequestTracer", "NullRequestTracer", "NULL_TRACER",
           "resolve_tracer", "LatencyReservoir", "validate_span_chain",
           "fleet_trace", "REQUEST_TRACE_CAP", "EVENTS_PER_REQUEST_CAP"]

# bounds: a week-long serving job must not grow tracer state without
# limit — oldest REQUESTS evict first (the recent window is the one an
# operator pulls a trace for), and a runaway per-request stream (long
# decode) stops recording past the per-request cap (counted, so the
# drop is visible).  BULK spans (prefill chunks, sampled decode steps)
# stop _LIFECYCLE_RESERVE entries early: the handful of lifecycle
# INSTANTS (finish, preempt, requeue marks) always have room, so a
# production-length generation's lane still shows how it ended
REQUEST_TRACE_CAP = 4096
EVENTS_PER_REQUEST_CAP = 256
_LIFECYCLE_RESERVE = 16     # cap slice bulk spans may not consume
_METRIC_FLUSH = 64          # batched counter-update granularity


class NullRequestTracer:
    """No-op stub with the full tracer surface: the ``tracer=False``
    engine/router drop-in, and the overhead bench's control arm."""

    enabled = False

    def event(self, rid, kind, ts=None, **args):
        pass

    def span(self, rid, kind, start, end, **args):
        pass

    def sample_span(self, rid, kind, start, end, every=1, **args):
        pass

    def events(self, rid) -> List[tuple]:
        return []

    def request_ids(self) -> List[int]:
        return []

    def kind_count(self, rid, kind) -> int:
        return 0

    def dropped(self) -> int:
        return 0

    def clear(self):
        pass

    def flush_metrics(self):
        pass

    def chrome_events(self, rename=None) -> List[dict]:
        return []


NULL_TRACER = NullRequestTracer()


class RequestTracer:
    """Bounded, thread-safe per-request phase log.

    Entries are ``(ph, kind, t_start, t_end, args)`` tuples per request
    id — ``ph`` is the chrome phase ("X" completed span, "i" instant) —
    appended in lifecycle order under one lock (router thread + engine
    step thread + an HTTP scraper may interleave).  Timestamps are
    ``time.perf_counter`` seconds: every tracer in the process shares
    the clock, so :func:`fleet_trace` merges them onto one timeline
    with no per-source renormalization.
    """

    enabled = True

    def __init__(self, max_requests: int = REQUEST_TRACE_CAP,
                 max_events_per_request: int = EVENTS_PER_REQUEST_CAP):
        self.max_requests = max(1, int(max_requests))
        self.max_events_per_request = max(1, int(max_events_per_request))
        # completed spans stop here; instants may fill the rest — the
        # lifecycle reserve (see _LIFECYCLE_RESERVE)
        self._span_cap = max(1, self.max_events_per_request
                             - _LIFECYCLE_RESERVE)
        self._lock = threading.Lock()
        # rid -> {"events": [entry], "counts": {kind: n}, "dropped": n}
        # (plain dict: insertion-ordered on py3.7+, and the hot path is
        # one lookup + one list append — this sits inside the engine
        # step loop, so every dict op counts)
        self._reqs: Dict[int, dict] = {}
        self._dropped_total = 0
        from .metrics import default_registry
        r = default_registry()
        self._m_spans = r.counter(
            "request_trace_spans_total",
            "phase spans/events recorded by request tracers in this "
            "process (flushed in batches off the record hot path)")
        self._m_dropped = r.counter(
            "request_trace_dropped_spans_total",
            "spans dropped at the per-request event cap (the bound "
            "that keeps a week-long stream from growing tracer state)")
        # Prometheus counter updates are BATCHED: the per-record cost
        # budget is one tracer lock + one list append — pending deltas
        # accumulate under that same lock, flushing every
        # _METRIC_FLUSH records and (force-)on every read path, so the
        # scrape lags by at most one batch while traffic flows and by
        # nothing once anyone looks
        self._pend_spans = 0
        self._pend_dropped = 0

    # ---- recording ------------------------------------------------------
    def _rec_locked(self, rid: int) -> dict:
        """The request's record; caller holds the lock.  Creating one
        past the request cap evicts the oldest (dict insertion order =
        recording order)."""
        rec = self._reqs.get(rid)
        if rec is None:
            rec = {"events": [], "counts": {}, "dropped": 0}
            self._reqs[rid] = rec
            while len(self._reqs) > self.max_requests:
                del self._reqs[next(iter(self._reqs))]
        return rec

    def _flush_locked(self, force: bool = False):
        """Push batched deltas into the Prometheus counters; caller
        holds the lock."""
        if not force and self._pend_spans < _METRIC_FLUSH \
                and self._pend_dropped < _METRIC_FLUSH:
            return
        ns, nd = self._pend_spans, self._pend_dropped
        self._pend_spans = self._pend_dropped = 0
        if ns:
            self._m_spans.inc(ns)
        if nd:
            self._m_dropped.inc(nd)

    def flush_metrics(self):
        """Force the batched span/drop counts into the counters (a
        scraper that must see exact figures calls this first)."""
        with self._lock:
            self._flush_locked(force=True)

    def _record(self, rid: int, entry: tuple) -> bool:
        # instants are lifecycle marks (finish/preempt/requeue/...):
        # they may use the reserved tail of the cap that bulk spans
        # cannot, so a long generation's lane still shows how it ended
        cap = (self.max_events_per_request if entry[0] == "i"
               else self._span_cap)
        with self._lock:
            rec = self._rec_locked(rid)
            if len(rec["events"]) >= cap:
                rec["dropped"] += 1
                self._dropped_total += 1
                self._pend_dropped += 1
                ok = False
            else:
                rec["events"].append(entry)
                self._pend_spans += 1
                ok = True
            self._flush_locked()
        return ok

    def event(self, rid: int, kind: str, ts: Optional[float] = None,
              **args):
        """One instant lifecycle event (enqueue, dispatch, requeue,
        first_token, finish, ...) at ``ts`` (perf_counter; default
        now)."""
        if ts is None:
            ts = time.perf_counter()
        self._record(int(rid), ("i", kind, float(ts), float(ts), args))

    def span(self, rid: int, kind: str, start: float, end: float,
             **args):
        """One completed phase span (pending wait, on-engine segment,
        prefill chunk, ...)."""
        self._record(int(rid),
                     ("X", kind, float(start), float(end), args))

    def sample_span(self, rid: int, kind: str, start: float, end: float,
                    every: int = 1, **args):
        """A span recorded every ``every``-th call per (request, kind)
        — the decode hot loop's knob: one sample per N steps keeps a
        long generation's trace readable AND inside the event cap,
        while the per-kind call count stays exact.  ONE lock pass for
        count + append (this is the per-step-per-slot call)."""
        rid = int(rid)
        if every < 1:
            every = 1
        with self._lock:
            rec = self._rec_locked(rid)
            counts = rec["counts"]
            n = counts.get(kind, 0)
            counts[kind] = n + 1
            if n % every:
                return
            if len(rec["events"]) >= self._span_cap:
                rec["dropped"] += 1
                self._dropped_total += 1
                self._pend_dropped += 1
            else:
                args["sample_index"] = n
                rec["events"].append(
                    ("X", kind, float(start), float(end), args))
                self._pend_spans += 1
            self._flush_locked()

    # ---- reads ----------------------------------------------------------
    # every read path force-flushes the batched counter deltas first:
    # once traffic stops, the next scrape/inspection sees exact totals
    # (the batch bounds scrape lag by records, reads bound it in time)
    def events(self, rid: int) -> List[tuple]:
        """The request's entries, lifecycle order (copies)."""
        with self._lock:
            self._flush_locked(force=True)
            rec = self._reqs.get(int(rid))
            return list(rec["events"]) if rec else []

    def kind_count(self, rid: int, kind: str) -> int:
        """Exact per-kind call count (sample_span records a subset but
        counts every call)."""
        with self._lock:
            self._flush_locked(force=True)
            rec = self._reqs.get(int(rid))
            return rec["counts"].get(kind, 0) if rec else 0

    def request_ids(self) -> List[int]:
        with self._lock:
            self._flush_locked(force=True)
            return list(self._reqs)

    def dropped(self) -> int:
        with self._lock:
            self._flush_locked(force=True)
            return self._dropped_total

    def clear(self):
        with self._lock:
            self._flush_locked(force=True)
            self._reqs.clear()
            self._dropped_total = 0

    # ---- chrome emission -------------------------------------------------
    def chrome_events(self, rename: Optional[Callable] = None
                      ) -> List[dict]:
        """Chrome trace dicts with ABSOLUTE perf_counter-second ``ts``
        (``merge_chrome_trace`` owns the shared-clock shift and the µs
        scaling).  Each request renders as one lane: ``tid`` = its id.

        ``rename(rid)`` maps local ids to display ids — the router uses
        it to rename engine-local request ids to fleet-wide rids so a
        request keeps ONE lane id across every engine it visited;
        ``None`` from the mapper keeps the local id on an offset lane
        (requests the router never routed)."""
        with self._lock:
            self._flush_locked(force=True)
            items = [(rid, list(rec["events"]))
                     for rid, rec in self._reqs.items()]
        out: List[dict] = []
        lanes: Dict[int, str] = {}
        for rid, evs in items:
            disp = rename(rid) if rename is not None else rid
            if disp is None:
                tid, label = 1_000_000 + rid, "local req %d" % rid
            else:
                tid, label = int(disp), "req %d" % int(disp)
            lanes[tid] = label
            for ph, kind, t0, t1, args in evs:
                ev = {"name": kind, "cat": "request", "ph": ph,
                      "tid": tid, "ts": t0}
                if ph == "X":
                    ev["dur"] = t1 - t0
                else:
                    ev["s"] = "t"
                if args:
                    ev["args"] = dict(args)
                out.append(ev)
        for tid in sorted(lanes):
            out.append({"name": "thread_name", "ph": "M", "tid": tid,
                        "args": {"name": lanes[tid]}})
        return out


def resolve_tracer(arg) -> "RequestTracer":
    """The engine/router ``tracer=`` knob: ``None``/``True`` -> a fresh
    bounded tracer (the default-ON contract), ``False`` -> the no-op
    stub, an existing tracer instance -> shared as-is."""
    if arg is None or arg is True:
        return RequestTracer()
    if arg is False:
        return NULL_TRACER
    if isinstance(arg, (RequestTracer, NullRequestTracer)):
        return arg
    raise TypeError(
        "tracer= must be None/True (own bounded tracer), False (no-op "
        "stub) or a RequestTracer instance; got %r" % (arg,))


class LatencyReservoir:
    """Bounded uniform reservoir (Algorithm R, seeded RNG so digests
    are deterministic for a fixed completion order) feeding p50/p95/p99
    TTFT/TPOT digests.  O(1) add, O(cap·log cap) quantile — quantiles
    run per COMPLETION (rare) not per step, so the sort never sits on
    the decode hot path."""

    def __init__(self, capacity: int = 1024, seed: int = 0):
        import numpy as np
        self.capacity = max(1, int(capacity))
        self._buf = np.zeros((self.capacity,), np.float64)
        self._n = 0                  # filled slots
        self._seen = 0               # values offered ever
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def add(self, value: float):
        v = float(value)
        with self._lock:
            self._seen += 1
            if self._n < self.capacity:
                self._buf[self._n] = v
                self._n += 1
            else:
                j = int(self._rng.randint(0, self._seen))
                if j < self.capacity:
                    self._buf[j] = v

    @property
    def count(self) -> int:
        """Values ever offered (the reservoir holds a uniform sample of
        them)."""
        with self._lock:
            return self._seen

    def _snapshot(self):
        """(seen, filled, buffer copy) under ONE lock acquisition."""
        with self._lock:
            return self._seen, self._n, self._buf[:self._n].copy()

    def quantile(self, q: float) -> Optional[float]:
        import numpy as np
        _seen, n, buf = self._snapshot()
        if not n:
            return None
        return float(np.quantile(buf, min(1.0, max(0.0, float(q)))))

    def digest(self) -> Dict[str, object]:
        """JSON-able {count, window, p50, p95, p99} (None quantiles
        when empty — valid JSON, unlike NaN).  All fields come from
        ONE locked snapshot, so a concurrent ``add`` can never yield
        an internally inconsistent digest (p50 > p95, count drifted
        from the quantiles' window)."""
        import numpy as np
        seen, n, buf = self._snapshot()
        if not n:
            return {"count": seen, "window": 0,
                    "p50": None, "p95": None, "p99": None}
        p50, p95, p99 = (float(v) for v in
                         np.quantile(buf, (0.5, 0.95, 0.99)))
        return {"count": seen, "window": n,
                "p50": p50, "p95": p95, "p99": p99}


# ---------------------------------------------------------------------------
# span-chain completeness (the bench gate's validator)
# ---------------------------------------------------------------------------
def validate_span_chain(events: List[tuple], eps: float = 0.005
                        ) -> Tuple[bool, str]:
    """Is this router-side request chain complete and gap-free?

    Structural contract (the kinds the ServingRouter records):

    - first event ``enqueue``, last event ``finish`` (exactly one);
    - ``dispatch`` only from the pending state, ``requeue`` only from
      the dispatched state — every preempt/engine-lost hop is followed
      by a re-dispatch (or by ``finish``, when the requeued tokens had
      already met the budget);
    - one ``on_engine`` span per dispatch (closed at requeue or
      completion).

    Temporal contract: the ``dispatch`` spans (each covers its pending
    wait, submit-or-requeue .. placement) + ``on_engine`` spans TILE
    the request's life — sorted by start, each span begins within
    ``eps`` of the running coverage end, starting at the enqueue mark
    and reaching the finish mark.  A missing hop record (e.g. an
    engine segment nobody closed) is a hole, not a rendering quirk.

    Returns ``(ok, reason)``; reason is "" on success.
    """
    if not events:
        return False, "no events recorded"
    kinds = [e[1] for e in events]
    if kinds[0] != "enqueue":
        return False, "chain does not start with enqueue (got %r)" \
            % kinds[0]
    if kinds.count("finish") != 1 or kinds[-1] != "finish":
        return False, "chain must end with exactly one finish"
    state = "pending"
    n_dispatch = n_requeue = 0
    for k in kinds:
        if k == "dispatch":
            if state != "pending":
                return False, "dispatch while already dispatched"
            state = "dispatched"
            n_dispatch += 1
        elif k == "requeue":
            if state != "dispatched":
                return False, "requeue without a live dispatch"
            state = "pending"
            n_requeue += 1
    if n_dispatch == 0:
        return False, "request was never dispatched"
    n_engine_spans = sum(1 for e in events
                         if e[0] == "X" and e[1] == "on_engine")
    if n_engine_spans != n_dispatch:
        return False, ("%d dispatches but %d on_engine spans"
                       % (n_dispatch, n_engine_spans))
    t_enqueue = events[0][2]
    t_finish = events[-1][2]
    spans = sorted(((e[2], e[3]) for e in events
                    if e[0] == "X" and e[1] in ("dispatch", "on_engine")),
                   key=lambda s: s[0])
    if not spans:
        return False, "no dispatch/on_engine coverage spans"
    if spans[0][0] > t_enqueue + eps:
        return False, "coverage starts %.3fs after enqueue" \
            % (spans[0][0] - t_enqueue)
    end = spans[0][1]
    for s0, s1 in spans[1:]:
        if s0 > end + eps:
            return False, "gap of %.3fs in span coverage" % (s0 - end)
        end = max(end, s1)
    if end < t_finish - eps:
        return False, "coverage ends %.3fs before finish" \
            % (t_finish - end)
    return True, ""


# ---------------------------------------------------------------------------
# fleet-wide chrome trace
# ---------------------------------------------------------------------------
def fleet_trace(path: str, router, device_trace_dir: Optional[str] = None,
                runtime_events=()) -> Dict[str, object]:
    """Write ONE chrome://tracing JSON for a :class:`ServingRouter`
    fleet: the router's request spans plus every pool engine's spans,
    each as its own track group (pid), all on the shared
    ``perf_counter`` clock, with chrome flow events linking a requeued
    request's segments across engines.

    Engine-local request ids are renamed to fleet-wide rids through the
    router's hop records (``RouterRequest.hops``), so one request keeps
    one lane id everywhere it ran; engine-side requests the router
    never placed (direct ``add_request`` callers) keep their local ids
    on offset lanes.

    Returns ``{path, engine_groups, flow_links, cross_engine_links,
    requests}`` — the bench gates on ``engine_groups >= 2`` and
    ``cross_engine_links >= 1`` under the kill drill.
    """
    from .trace_merge import merge_chrome_trace
    tracer = getattr(router, "tracer", None) or NULL_TRACER

    # every request the router knows about: finished, in flight, AND
    # requeued-but-not-yet-redispatched (router.pending) — the
    # mid-incident case an operator pulls a trace FOR; omitting
    # pending would strip a drained request's lane renaming and hop
    # arrows exactly when they matter
    recs = list(getattr(router, "finished", {}).values())
    inflight = getattr(router, "_inflight", None)
    if inflight:
        recs += list(inflight.values())
    recs += [rr for rr in getattr(router, "pending", ())
             if getattr(rr, "hops", None)]
    rid_map: Dict[Tuple[int, int], int] = {}
    hops_by_rid: Dict[int, list] = {}
    for rr in recs:
        hops = list(getattr(rr, "hops", ()))
        hops_by_rid[rr.rid] = hops
        for hop in hops:
            rid_map[(hop[0], hop[1])] = rr.rid

    groups: List[Tuple[str, List[dict]]] = []
    router_events = tracer.chrome_events() if tracer.enabled else []
    if router_events:
        groups.append(("router", router_events))

    engine_events: Dict[int, List[dict]] = {}
    for h in router.handles.values():
        etr = getattr(h.engine, "tracer", None)
        evs: List[dict] = []
        if etr is not None and getattr(etr, "enabled", False):
            eid = h.engine_id
            evs = etr.chrome_events(
                rename=lambda erid, _e=eid: rid_map.get((_e, erid)))
        engine_events[h.engine_id] = evs

    # flow events: one s->f arrow per hop pair, drawn from the source
    # segment's leave mark to the destination segment's dispatch mark.
    # Arrows bind to enclosing slices in chrome, so they are only
    # emitted between groups that actually carry spans (a stub-traced
    # engine gets neither dangling arrows nor a phantom track group —
    # the completeness gates must not pass on hop records alone)
    spanned = {eid for eid, evs in engine_events.items() if evs}
    flow_links = cross_links = 0
    for rid, hops in hops_by_rid.items():
        for i in range(len(hops) - 1):
            src, dst = hops[i], hops[i + 1]
            if src[3] is None or dst[2] is None:
                continue              # segment still open: no arrow yet
            if src[0] not in spanned or dst[0] not in spanned:
                continue
            fid = rid * 1000 + i
            name = "req %d requeue" % rid
            engine_events[src[0]].append(
                {"name": name, "cat": "flow", "ph": "s", "id": fid,
                 "tid": rid, "ts": float(src[3])})
            engine_events[dst[0]].append(
                {"name": name, "cat": "flow", "ph": "f", "bp": "e",
                 "id": fid, "tid": rid, "ts": float(dst[2])})
            flow_links += 1
            if src[0] != dst[0]:
                cross_links += 1

    n_engine_groups = 0
    for eid in sorted(engine_events):
        if eid in spanned:
            groups.append(("engine %d" % eid, engine_events[eid]))
            n_engine_groups += 1

    merge_chrome_trace(path, host_events=None,
                       runtime_events=list(runtime_events),
                       device_trace_dir=device_trace_dir,
                       extra_groups=groups)
    return {"path": path, "engine_groups": n_engine_groups,
            "flow_links": flow_links, "cross_engine_links": cross_links,
            "requests": len(hops_by_rid)}
