"""Metric exporters: Prometheus text format, /metrics HTTP endpoint,
JSON snapshot dump.

The reference repo has no in-tree exporter (SURVEY §5.5 "No
Prometheus-style exporter in-repo"); this closes the gap with stdlib
only — ``http.server`` on a background thread, no third-party client
library.

Usage::

    from paddle_tpu.observability import start_metrics_server
    srv = start_metrics_server()          # port from PADDLE_TPU_METRICS_PORT
    ...                                   # GET :port/metrics  /healthz
    srv.stop()                            # clean shutdown (joins thread)
"""
from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .metrics import (MetricsRegistry, _HistogramChild, default_registry)

__all__ = ["generate_latest", "json_snapshot", "dump_json",
           "MetricsServer", "start_metrics_server", "METRICS_PORT_ENV",
           "set_health_provider", "healthz_payload"]

METRICS_PORT_ENV = "PADDLE_TPU_METRICS_PORT"

CONTENT_TYPE_LATEST = "text/plain; version=0.0.4; charset=utf-8"

# process-wide /healthz payload provider (e.g. a serving engine's
# ``health_payload`` bound method): its dict is merged into the healthz
# JSON body so an admission plane scrapes load (occupancy, free pages,
# chunk-queue depth, engine id) without parsing Prometheus text
_health_provider = None


def set_health_provider(provider) -> None:
    """Install (or clear, with ``None``) the process-wide callable whose
    dict enriches every ``/healthz`` response.  Typical use::

        set_health_provider(engine.health_payload)
    """
    global _health_provider
    _health_provider = provider


def healthz_payload(provider=None) -> dict:
    """The ``/healthz`` JSON body.  Always contains ``status: "ok"`` —
    the bare 200-with-"ok" contract existing callers probe — plus the
    provider's load fields when one is installed.  A raising or
    non-dict provider degrades to the bare payload: a liveness probe
    must never 500 because a stats callback broke."""
    payload = {"status": "ok"}
    provider = provider or _health_provider
    if provider is not None:
        try:
            extra = provider()
            if isinstance(extra, dict):
                extra = dict(extra)         # never mutate the
                extra.pop("status", None)   # provider's own dict;
                payload.update(extra)       # liveness field is ours
        except Exception:                             # noqa: BLE001
            pass
    return payload


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(text: str) -> str:
    return (text.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if v != v:
        return "NaN"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labels_str(labels: dict, extra: Optional[dict] = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in items.items())
    return "{" + inner + "}"


def generate_latest(registry: Optional[MetricsRegistry] = None) -> bytes:
    """The registry rendered in the Prometheus text exposition format
    (version 0.0.4) — what ``GET /metrics`` serves."""
    registry = registry or default_registry()
    lines = []
    for metric in registry.collect():
        if metric.help:
            lines.append(f"# HELP {metric.name} "
                         f"{_escape_help(metric.help)}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        for child in metric.children():
            if isinstance(child, _HistogramChild):
                cum = child.cumulative()
                for bound, acc in zip(metric.buckets, cum):
                    lines.append(
                        f"{metric.name}_bucket"
                        f"{_labels_str(child.labels, {'le': '%g' % bound})}"
                        f" {acc}")
                lines.append(
                    f"{metric.name}_bucket"
                    f"{_labels_str(child.labels, {'le': '+Inf'})}"
                    f" {cum[-1]}")
                lines.append(f"{metric.name}_sum"
                             f"{_labels_str(child.labels)} "
                             f"{_fmt_value(child.sum)}")
                lines.append(f"{metric.name}_count"
                             f"{_labels_str(child.labels)} {child.count}")
            else:
                lines.append(f"{metric.name}{_labels_str(child.labels)} "
                             f"{_fmt_value(child.value)}")
    return ("\n".join(lines) + "\n").encode("utf-8")


def json_snapshot(registry: Optional[MetricsRegistry] = None) -> dict:
    """JSON-able snapshot of every series (the machine-readable twin of
    :func:`generate_latest`; ``bench.py --emit-metrics`` dumps this)."""
    return (registry or default_registry()).snapshot()


def dump_json(path: str,
              registry: Optional[MetricsRegistry] = None) -> str:
    """Atomically write the JSON snapshot to ``path`` (temp + rename, so
    a concurrent scraper never reads a half-written file)."""
    snap = json_snapshot(registry)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class _Handler(BaseHTTPRequestHandler):
    # the server thread must never block scraping on a slow reverse DNS
    # lookup, and per-request stderr chatter is noise in a train log
    def log_message(self, fmt, *args):                # noqa: A002
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):                                 # noqa: N802
        path = self.path.split("?", 1)[0]
        if path in ("/metrics", "/"):
            try:
                body = generate_latest(self.server._registry)
            except Exception as e:                    # noqa: BLE001
                self._send(500, repr(e).encode(), "text/plain")
                return
            self._send(200, body, CONTENT_TYPE_LATEST)
        elif path == "/healthz":
            try:
                # default=str: numpy scalars (this codebase's natural
                # numeric type) serialize as digit strings, which
                # scrapers int()/float() fine
                body = json.dumps(
                    healthz_payload(
                        getattr(self.server, "_health_provider", None)),
                    default=str) + "\n"
            except Exception:                         # noqa: BLE001
                # the liveness contract outranks the stats payload
                body = '{"status": "ok"}\n'
            self._send(200, body.encode("utf-8"), "application/json")
        else:
            self._send(404, b"not found\n", "text/plain")


class MetricsServer:
    """Background-thread HTTP endpoint serving ``/metrics`` (Prometheus
    text format) and ``/healthz``.

    Port resolution: explicit ``port`` arg, else the
    ``PADDLE_TPU_METRICS_PORT`` env var, else 0 (OS-assigned ephemeral —
    read the bound port back from ``.port``).  ``stop()`` shuts the
    listener down cleanly and joins the serving thread.
    """

    def __init__(self, port: Optional[int] = None, addr: str = "0.0.0.0",
                 registry: Optional[MetricsRegistry] = None,
                 health_provider=None):
        if port is None:
            port = int(os.environ.get(METRICS_PORT_ENV, "0") or 0)
        self.addr = addr
        self._requested_port = int(port)
        self.registry = registry or default_registry()
        # per-server /healthz enrichment (falls back to the
        # process-wide set_health_provider when None)
        self.health_provider = health_provider
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.addr, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd._registry = self.registry
        httpd._health_provider = self.health_provider
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.2},
            name="pdtpu-metrics-http", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()           # stops serve_forever
            httpd.server_close()       # releases the listening socket
        if thread is not None:
            thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def start_metrics_server(port: Optional[int] = None,
                         addr: str = "0.0.0.0",
                         registry: Optional[MetricsRegistry] = None,
                         health_provider=None) -> MetricsServer:
    """Convenience: construct + start a :class:`MetricsServer`."""
    return MetricsServer(port=port, addr=addr, registry=registry,
                         health_provider=health_provider).start()
