"""Continuous batching over the paged KV cache.

Parity: the reference serving stack's batched multi-request execution —
block_multihead_attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)
driven by a request scheduler around AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:210 ZeroCopyRun).

TPU-native design: the scheduler keeps a fixed number of decode SLOTS and
one engine step is ONE jitted XLA module (jit/serving_step.DecodeStep)
at that fixed slot count — all layers, the paged cache append, paged
attention, the LM head and greedy sampling fused, with the per-layer KV
pools donated so the append is an in-place HBM write.  Inactive slots
are masked (token 0, seq_len 0, block table aimed at the cache's sink
page), never dropped, so admission/eviction churn never changes a traced
shape and the decode step compiles exactly once for the engine's
lifetime.

Prefill (Ragged Paged Attention, arXiv:2604.15464: mixed-length prefill
without per-shape recompilation) has three coordinated layers:

- **Bucketed**: with ``prefill_buckets`` set, prompts pad to a small
  geometric set of length buckets and admission runs ONE compiled
  ``PrefillStep`` per bucket (masked forward + fused page scatter +
  on-device first-token sample), so total prefill compiles are bounded
  by the bucket count instead of the prompt-length distribution.
- **Chunked**: prompts longer than ``prefill_chunk_size`` split into
  fixed-size chunks processed one per ``step()`` interleaved with
  decode, so a long prompt never stalls every running request's TPOT.
  Chunk offset is a traced scalar — chunks reuse the bucket compiles.
- **Prefix cached** (``enable_prefix_cache``): refcounted KV pages plus
  a block-granularity prompt-prefix hash table
  (inference/prefix_cache.PrefixPageCache); an admitted request whose
  prefix hits shares those pages (refcount++, copy-on-write on the
  first partial page) and only prefills the suffix.  Eviction honors
  refcounts — a shared page is never reclaimed from under a live
  request's block table.

**Mixed single-step mode** (``mixed_step=True``) supersedes the
prefill/decode module split entirely: every engine step packs the whole
admission mix — each running slot as a length-1 decode span, each
prefilling slot's next chunk as a length-C span, as many chunks as the
budget holds — into ONE fused ``MixedStep`` launch over the ragged
paged attention kernel (arXiv:2604.15464).  Total tokens pad to a small
geometric budget set, so compiles are bounded by the budget count, long
prompts no longer pay one engine round per chunk, and prefill never
stalls running TPOT.  The bucketed PrefillStep and legacy dense paths
remain for ``mixed_step=False`` (the default — existing engines are
byte-identical).

Sampling + speculative decoding (round 14, both OFF by default):

- **Stochastic sampling** (``sampling=True``): per-request temperature
  / top-k / top-p / seed ride ``add_request`` and reach the fused
  steps as traced data (the mixed pack grows four bitcast columns, the
  split steps one [.., 4] int32 operand), sampled on device with a
  counter-based PRNG keyed on (request seed, token position) — so a
  sampled request's tokens are identical alone or batched, split or
  mixed, single-chip or tp, and changing knobs/seeds never retraces.
  ``temperature=0`` requests take the exact greedy argmax.
- **Speculative decoding** (``draft_model=``, needs ``mixed_step``): a
  small draft model with its OWN per-layer paged pools — addressed by
  the SAME page ids, so allocation/refcount/COW bookkeeping is shared
  and prefix-cache hits carry draft KV for free — proposes ``spec_k``
  tokens per engine round (k fused draft launches; prefill chunks
  mirror into the draft pool in the same launches), and the target
  verifies every slot's k+1 positions in ONE MixedStep launch using
  length-(k+1) ragged spans.  Standard accept/reject with
  rejection-resampling keeps the sampled output distribution exact;
  greedy speculative output is BYTE-IDENTICAL to non-speculative
  greedy (the CPU-checkable gate in ``bench_serving.py
  --speculative``).  Pages grown for rejected draft positions roll
  back through the refcounted release path (lazy mode).

Admission/eviction is host control flow; all math is jitted device
compute, and the only per-step host traffic is the [slots] int32
next-token fetch (plus one int32 scalar per non-mixed prefill chunk;
a speculative round adds the k [slots] draft-token fetches and the
verifier's [slots] accepted-count row — draft DISTRIBUTIONS stay on
device).
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..ops.paged_attention import PagedKVCache

# process-wide engine-id sequence: a multi-engine router needs a stable
# identity per engine for health gauges / the /healthz payload, and an
# explicit engine_id= keeps ids meaningful across processes
_ENGINE_IDS = itertools.count()


@dataclass
class GenerationRequest:
    """One in-flight generation (parity: the request objects the
    reference serving runtime schedules)."""
    req_id: int
    prompt_ids: np.ndarray                 # [L] int
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    output_ids: List[int] = field(default_factory=list)
    state: str = "waiting"        # waiting -> [prefilling ->] running -> done
    # True when the engine ran out of KV pages mid-decode and finished
    # this request early instead of wedging the whole batch
    truncated: bool = False

    # slot bookkeeping (set while running)
    slot: int = -1
    seq_len: int = 0
    block_ids: List[int] = field(default_factory=list)
    # chunked-prefill progress: prompt tokens already in cache pages
    # (starts at the prefix-cache hit length)
    prefill_pos: int = 0
    # prompt tokens served from shared prefix pages instead of recompute
    prefix_hit_tokens: int = 0
    # stochastic sampling (round 14): temperature <= 0 is exact greedy;
    # seed feeds the per-position counter-based PRNG
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0
    seed: int = 0
    # n>1 generation groups: a child admits only after its parent's
    # prefill published the shared prefix pages (COW machinery)
    parent_req: Optional["GenerationRequest"] = field(default=None,
                                                     repr=False)
    # speculative decoding: positions [0, draft_len) hold draft-model
    # KV for the ACCEPTED token sequence
    draft_len: int = 0
    # telemetry marks (perf_counter): admission -> first token = TTFT,
    # first token -> done over n-1 tokens = TPOT
    t_submit: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0


class ContinuousBatchingEngine:
    """Slot scheduler + single-compile batched paged decode for
    LlamaForCausalLM.

    add_request() may be called at any time (including between steps
    while other requests are mid-decode); step() advances every running
    request by one token.  Greedy decoding — interleaved execution is
    bit-identical to running each request alone (the test contract).

    ``max_seq_len`` bounds prompt + generation per request and fixes the
    block-table width (the compiled decode step's shape); it defaults to
    the pool's fair share per slot, num_blocks * block_size //
    max_batch_size.

    ``prefill_buckets``: None (default) keeps the legacy dense prefill
    (one eager forward per prompt, re-traced per distinct length);
    ``"auto"`` derives a geometric 32/64/.../top set from max_seq_len;
    a tuple uses those widths.  ``prefill_chunk_size`` defaults to the
    top bucket.  ``enable_prefix_cache`` requires buckets or
    ``mixed_step`` (suffix-only prefill needs an offset-carrying
    compiled step).

    ``mixed_step=True`` replaces BOTH the decode module and the
    prefill buckets with one fused step per total-token budget
    (``token_budgets``: ``"auto"`` geometric set covering all-decode up
    to slots+chunk, or an explicit tuple whose top must fit an
    all-decode pack).  ``prefill_chunk_size`` bounds a single span.

    ``mesh=`` (+ optional ``sharding=ShardingConfig(axis='tp')``)
    makes the engine multi-chip: every fused step runs tensor-parallel
    over the mesh's ``tp`` axis (see ``jit/spmd.py`` for the
    per-weight-family spec layout), with KV pools sharded over kv
    heads — per-chip pool HBM is 1/tp — and tokens byte-identical to
    the single-chip engine (BENCH_SERVE_r12.json gates this).
    Requires ``mixed_step=True`` or ``prefill_buckets`` (the legacy
    dense prefill is eager, single-chip math).

    Quantization (round 13; defaults off — the fp32/bf16 engine stays
    byte-identical):

    - ``kv_dtype="int8"``: the paged pools store int8 codes plus
      per-page-per-head fp32 absmax scales — ~4× (fp32) / ~2× (bf16)
      pages per HBM byte, scales counted.  Writes quantize inside the
      compiled steps, every attention path dequantizes into the same
      fp32 online-softmax, COW/prefix sharing carry scales with pages.
    - ``weight_quant="int8"``: per-output-channel absmax PTQ over the
      projection weights (``quantization.functional.
      quantize_param_tree``); the steps dequantize on use, so HBM
      holds the int8 tree (+ scale vectors) — ~4× smaller weights.
    - ``quant_collectives=True`` (needs ``mesh``): the tp logits
      all-gather moves int8 codes + per-shard scales (EQuARX-style,
      arXiv:2506.17615) instead of fp words.

    All three are TOLERANCE-gated, not parity-gated: the quantization
    bench (BENCH_QUANT_r13.json) reports greedy token-match rate vs
    the fp32 engine per workload against declared thresholds.  Both
    quant modes need a compiled prefill path (``mixed_step=True`` or
    ``prefill_buckets``) — the legacy dense prefill runs eager fp
    math and is rejected at construction.

    Request tracing (round 16): the engine owns a bounded
    ``RequestTracer`` (``tracer=`` kwarg; default ON, ``False`` = the
    no-op stub) recording typed per-request phase spans: enqueue,
    admit (+prefix hit), per-chunk prefill, sampled decode steps,
    first token, preempt, finish.  Host-side appends only, on the
    shared ``perf_counter`` clock; a ``ServingRouter`` merges every
    pool engine's spans into one fleet chrome trace (``fleet_trace``).

    KV page migration + disaggregation (round 19, defaults off):
    ``extract_request``/``inject_request`` move a running request's
    physical KV pages between engines as ONE batched host buffer per
    dtype (int8 scale rows travel free), so a preempted or
    engine-lost request resumes elsewhere with ZERO re-prefill;
    ``role="prefill"|"decode"|"mixed"`` labels this engine for the
    router's disaggregated dispatch (fresh prompts → prefill
    specialists, whose finished pages migrate to decode specialists);
    ``host_tier_bytes=N`` stacks a bounded host-RAM spill tier on the
    prefix cache — evicted-but-hot prefix pages spill to host instead
    of dying and restore on a later hit with one batched inject.
    """

    def __init__(self, model, max_batch_size: int = 8,
                 num_blocks: int = 256, block_size: int = 16,
                 max_seq_len: Optional[int] = None,
                 use_pallas: Optional[bool] = None,
                 lazy_alloc: bool = False,
                 prefill_buckets=None,
                 prefill_chunk_size: Optional[int] = None,
                 enable_prefix_cache: bool = False,
                 mixed_step: bool = False,
                 token_budgets="auto",
                 mesh=None, sharding=None,
                 kv_dtype: Optional[str] = None,
                 weight_quant: Optional[str] = None,
                 quant_collectives: bool = False,
                 sampling: bool = False,
                 draft_model=None, spec_k: int = 2,
                 engine_id: Optional[int] = None,
                 tracer=None,
                 role: str = "mixed",
                 host_tier_bytes: int = 0):
        from ..jit.serving_step import DecodeStep, MixedStep, PrefillStep
        self.model = model
        # disaggregated serving (round 19): a router routes fresh
        # prompts to "prefill" specialists (big token budgets, chunked)
        # and migrates their finished pages to "decode" specialists
        # (high slot counts, int8 KV); "mixed" engines take anything —
        # the default, so single-engine users never see role policy
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                "ContinuousBatchingEngine role must be 'prefill', "
                "'decode' or 'mixed'; got %r" % (role,))
        self.role = role
        # identity for multi-engine deployments (the ServingRouter's
        # health gauge + the /healthz payload key on it); defaults to a
        # process-wide sequence so standalone engines need no plumbing
        self.engine_id = int(next(_ENGINE_IDS) if engine_id is None
                             else engine_id)
        # ---- sampling / speculative validation (construction-time) --
        self.sampling = bool(sampling)
        if self.sampling and not mixed_step and not prefill_buckets:
            raise ValueError(
                "stochastic sampling needs a compiled prefill path: "
                "pass mixed_step=True or prefill_buckets='auto' — the "
                "legacy dense prefill argmaxes its first token eagerly "
                "and cannot apply per-request temperature/top-k/top-p")
        if draft_model is not None:
            if not mixed_step:
                raise ValueError(
                    "speculative decoding (draft_model=) needs "
                    "mixed_step=True: the target verifies all slots' "
                    "k+1 positions as length-(k+1) ragged spans in one "
                    "MixedStep launch")
            if mesh is not None or sharding is not None:
                raise ValueError(
                    "speculative decoding is single-chip for now: the "
                    "draft engine runs unsharded, so a tensor-parallel "
                    "target would mix placements — drop mesh/sharding "
                    "or drop draft_model")
            if int(spec_k) < 1:
                raise ValueError(
                    "spec_k must be >= 1 (the draft proposes at least "
                    "one token per round); got %r" % (spec_k,))
            if draft_model.config.vocab_size != model.config.vocab_size:
                raise ValueError(
                    "draft and target models must share one vocabulary "
                    "(%d vs %d): accept/reject compares token ids"
                    % (draft_model.config.vocab_size,
                       model.config.vocab_size))
        self.draft_model = draft_model
        self.spec_k = int(spec_k) if draft_model is not None else 0
        # ---- quantization validation (construction-time, PR-7 norm:
        # a clear error HERE, never a dtype/shape failure deep inside
        # tracing) --------------------------------------------------
        if kv_dtype not in (None, "float32", "bfloat16", "int8"):
            raise ValueError(
                "ContinuousBatchingEngine kv_dtype must be None (follow "
                "the model dtype), 'float32', 'bfloat16' or 'int8'; got "
                "%r" % (kv_dtype,))
        if weight_quant not in (None, "int8"):
            raise ValueError(
                "ContinuousBatchingEngine weight_quant must be None or "
                "'int8'; got %r" % (weight_quant,))
        if (kv_dtype == "int8" or weight_quant == "int8") \
                and not mixed_step and not prefill_buckets:
            raise ValueError(
                "quantized serving (kv_dtype='int8' / weight_quant="
                "'int8') needs a compiled prefill path: pass "
                "mixed_step=True or prefill_buckets='auto' — the legacy "
                "dense prefill runs the model eagerly in fp and writes "
                "unquantized K/V")
        if quant_collectives and mesh is None and sharding is None:
            raise ValueError(
                "quant_collectives=True quantizes the tensor-parallel "
                "logits all-gather; a single-chip engine has no "
                "collectives — pass mesh= (tp >= 2) or drop the flag")
        # ---- tensor-parallel serving (multi-chip) --------------------
        # mesh + ShardingConfig(axis='tp') shard the fused steps over
        # the tp axis (jit/spmd.py is the single source of the mesh /
        # per-weight-family spec logic, shared with TrainStep — pass a
        # co-located train mesh and its 'tp' axis resolves).  Head
        # divisibility and pool shape are validated HERE, not as a
        # shard_map shape failure deep in tracing.
        if mesh is not None or sharding is not None:
            from ..jit.spmd import tp_serving_context
            self.tp = tp_serving_context(model, mesh, sharding)
        else:
            self.tp = None
        self.tp_degree = self.tp.degree if self.tp is not None else 1
        self.fsdp_degree = self.tp.fsdp_degree \
            if self.tp is not None else 1
        self.cp_degree = self.tp.cp_degree if self.tp is not None else 1
        self.ep_degree = self.tp.ep_degree if self.tp is not None else 1
        # ---- context-parallel serving (round 22) --------------------
        # a 'cp' mesh axis stripes every pool's slot dim: validated
        # HERE with actionable messages (block_size divisibility, no
        # int8 pools, no legacy dense prefill, no spec-decode), never
        # as a shard_map shape failure deep in tracing
        if self.cp_degree > 1:
            from ..jit.spmd import validate_cp_serving
            validate_cp_serving(
                self.cp_degree, block_size,
                quantized_kv=(kv_dtype == "int8"),
                dense_prefill=(not mixed_step and not prefill_buckets),
                spec_decode=draft_model is not None)
        # ---- expert-parallel MoE serving (round 24) -----------------
        # an 'ep' mesh axis shards the expert banks' E dim: validated
        # HERE with actionable messages (expert-count divisibility, no
        # legacy dense prefill, no spec-decode), never as a shard_map
        # shape failure; the token budgets are re-checked after they
        # resolve below (every budget must stripe evenly over ep)
        if self.ep_degree > 1:
            from ..jit.spmd import validate_ep_serving
            validate_ep_serving(
                getattr(model.config, "num_local_experts", 0),
                self.ep_degree, mixed_step=bool(mixed_step),
                dense_prefill=(not mixed_step and not prefill_buckets),
                spec_decode=draft_model is not None)
        if quant_collectives and self.tp is None:
            raise ValueError(
                "quant_collectives=True but the mesh's tp axis "
                "degenerates to 1 chip — there is no logits all-gather "
                "to quantize; use tp >= 2 or drop the flag")
        if self.tp is not None and not mixed_step and not prefill_buckets:
            raise ValueError(
                "tensor-parallel serving needs a compiled prefill path: "
                "pass mixed_step=True or prefill_buckets='auto' (the "
                "legacy dense prefill runs the model eagerly on one "
                "chip and cannot feed head-sharded KV pools)")
        # lazy_alloc: pages are allocated as a sequence actually grows
        # instead of reserving the full prompt+budget footprint at
        # admission — higher occupancy for the same pool, at the cost
        # that the pool CAN run dry mid-decode.  When it does, the
        # victim request is finished early with ``truncated=True``
        # (robustness contract: step() never raises out of a full
        # batch; the other slots keep decoding).
        self.lazy_alloc = bool(lazy_alloc)
        cfg = model.config
        self.cfg = cfg
        # MoE dispatch accounting (round 24): every real token in a
        # mixed pack is routed to top_k experts in each MoE layer —
        # static per pack, counted host-side next to the collectives
        self._moe_layers = (cfg.num_hidden_layers
                            if getattr(cfg, "num_local_experts", 0)
                            else 0)
        self._moe_topk = int(getattr(cfg, "num_experts_per_tok", 0))
        self.max_batch_size = max_batch_size
        self.block_size = block_size
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.kv_quant = kv_dtype == "int8"
        self.caches = [
            PagedKVCache(num_blocks, block_size,
                         cfg.num_key_value_heads, self.head_dim, dtype,
                         sink_block=True, kv_dtype=kv_dtype)
            for _ in range(cfg.num_hidden_layers)]
        # per-channel absmax PTQ: quantize ONCE at construction; every
        # step consumes the same int8+scales tree via dequant-on-use
        if weight_quant == "int8":
            from ..quantization.functional import quantize_param_tree
            self.weight_qtree = quantize_param_tree(
                {k: t._value for k, t in model.state_dict().items()})
        else:
            self.weight_qtree = None
        self.quant_collectives = bool(quant_collectives)
        if self.tp is not None:
            # re-check against the pool actually built (paranoia for
            # subclasses that override cache construction), then place:
            # each chip holds only its kv-head slice of every page
            from ..jit.spmd import validate_tp_serving
            validate_tp_serving(cfg, self.tp_degree,
                                pool_kv_heads=self.caches[0].num_kv_heads)
            pool_sh = self.tp.pool_sharding()
            scale_sh = self.tp.kv_scale_sharding() if self.kv_quant \
                else None
            for c in self.caches:
                c.place(pool_sh, scale_sh)
        if max_seq_len is None:
            max_seq_len = max(block_size,
                              num_blocks * block_size // max_batch_size)
        self.max_seq_len = max_seq_len
        self.bt_width = -(-max_seq_len // block_size)
        self._sink = self.caches[0].sink
        self.slots: List[Optional[GenerationRequest]] = \
            [None] * max_batch_size
        self.waiting: List[GenerationRequest] = []
        self.finished: Dict[int, GenerationRequest] = {}
        self._next_id = 0
        # slot-padded device-step inputs (fixed shapes forever): masked
        # slots hold token 0 / seq_len 0 / an all-sink block-table row
        self._tokens = np.zeros((max_batch_size,), np.int32)
        self._seq_lens = np.zeros((max_batch_size,), np.int32)
        self._bt = np.full((max_batch_size, self.bt_width), self._sink,
                           np.int32)
        # per-slot packed sampling knobs (temperature bits, top_k,
        # top_p bits, seed); all-zero = greedy, the masked-slot default
        self._samp = np.zeros((max_batch_size, 4), np.int32)
        self.decode_step = DecodeStep(
            model, self.caches, use_pallas=use_pallas, tp=self.tp,
            weight_qparams=self.weight_qtree,
            quant_collectives=self.quant_collectives,
            sampling=self.sampling)

        # ---- bucketed / chunked prefill ------------------------------
        if prefill_buckets == "auto":
            buckets = self._auto_buckets(self.max_seq_len)
        elif prefill_buckets:
            buckets = tuple(sorted({int(b) for b in prefill_buckets}))
        else:
            buckets = None
        self.prefill_buckets = buckets
        if buckets:
            self.chunk_size = int(prefill_chunk_size or buckets[-1])
            if self.chunk_size > buckets[-1]:
                raise ValueError(
                    "prefill_chunk_size %d exceeds the top bucket %d — "
                    "every chunk must map to a compiled bucket"
                    % (self.chunk_size, buckets[-1]))
            self.prefill_step = PrefillStep(
                model, self.caches, self.bt_width, tp=self.tp,
                weight_qparams=self.weight_qtree,
                quant_collectives=self.quant_collectives,
                sampling=self.sampling)
        else:
            self.chunk_size = None
            self.prefill_step = None
        # ---- fused mixed prefill+decode step -------------------------
        # (Ragged Paged Attention): ONE compiled module per total-token
        # budget advances decode slots AND prefill chunks together —
        # no per-chunk engine round, no prefill/decode module split
        if mixed_step:
            if self.chunk_size is None:
                self.chunk_size = int(prefill_chunk_size
                                      or self._auto_buckets(
                                          self.max_seq_len)[-1])
            # a speculative all-decode pack is slots x (k+1) verify
            # tokens, not slots x 1 — size the budget base to it
            base_spans = max_batch_size * (self.spec_k + 1)
            if token_budgets == "auto":
                budgets = self._auto_budgets_mixed(base_spans,
                                                   self.chunk_size)
            else:
                budgets = tuple(sorted({int(b) for b in token_budgets}))
                if not budgets or budgets[-1] < base_spans:
                    raise ValueError(
                        "top token budget %r < %d (max_batch_size x "
                        "(spec_k+1)): an all-decode step would not fit"
                        % (token_budgets, base_spans))
            self.token_budgets = budgets
            if self.ep_degree > 1:
                from ..jit.spmd import validate_ep_serving
                validate_ep_serving(
                    getattr(cfg, "num_local_experts", 0),
                    self.ep_degree, budgets=budgets)
            self.mixed = MixedStep(model, self.caches, self.bt_width,
                                   max_spans=max_batch_size,
                                   # a verify span is spec_k+1 tokens —
                                   # the kernel's static span window
                                   # must cover it as well as a chunk
                                   span_q=min(max(self.chunk_size,
                                                  self.spec_k + 1),
                                              budgets[-1]),
                                   use_pallas=use_pallas, tp=self.tp,
                                   weight_qparams=self.weight_qtree,
                                   quant_collectives=
                                   self.quant_collectives,
                                   sampling=self.sampling,
                                   spec_k=self.spec_k)
            # padding tokens spread over the sink page's slots
            self._dest_pad = (np.arange(budgets[-1], dtype=np.int32)
                              % block_size)
        else:
            self.token_budgets = None
            self.mixed = None
        # ---- speculative draft engine --------------------------------
        # the draft model's OWN per-layer pools, addressed by the SAME
        # page ids as the target's (caches[0] stays the one free-list /
        # refcount authority) — prefix sharing, COW and release carry
        # the draft KV for free.  The draft runs as a MixedStep too:
        # catch-up spans are ragged (1-2 tokens) and prefill chunks
        # mirror straight into the draft pool.
        if draft_model is not None:
            dcfg = draft_model.config
            d_dtype = jnp.bfloat16 if dcfg.dtype == "bfloat16" \
                else jnp.float32
            self.draft_caches = [
                PagedKVCache(num_blocks, block_size,
                             dcfg.num_key_value_heads,
                             dcfg.hidden_size // dcfg.num_attention_heads,
                             d_dtype, sink_block=True)
                for _ in range(dcfg.num_hidden_layers)]
            self.draft_step = MixedStep(
                draft_model, self.draft_caches, self.bt_width,
                max_spans=max_batch_size,
                span_q=min(self.chunk_size, self.token_budgets[-1]),
                use_pallas=use_pallas, sampling=self.sampling,
                return_probs=self.sampling)
            # draft packs are SMALL (proposal launches carry one token
            # per slot, catch-up at most two) — give the draft set
            # tight small bases so a 1-token-per-slot launch never pads
            # to the verify-sized budget, and carry the target's set on
            # top so chunk mirrors always fit.  Both modules' compiles
            # stay bounded by their (static) budget-set sizes.
            small = []
            b = 1
            while b < max(1, max_batch_size):
                b *= 2
            small.append(b)
            small.append(b * 2)                  # catch-up: <= 2 tokens
            self.draft_budgets = tuple(sorted(
                set(small) | set(self.token_budgets)))
            self._zero_q = (jnp.zeros((max_batch_size, cfg.vocab_size),
                                      jnp.float32)
                            if self.sampling else None)
        else:
            self.draft_caches = []
            self.draft_step = None
            self.draft_budgets = None
            self._zero_q = None
        # ---- host-RAM prefix spill tier (round 19) -------------------
        if host_tier_bytes and not enable_prefix_cache:
            raise ValueError(
                "host_tier_bytes is the prefix cache's spill tier: "
                "pass enable_prefix_cache=True (there is nothing to "
                "spill without a prefix table)")
        if host_tier_bytes and self.tp is not None:
            raise ValueError(
                "the host spill tier is single-chip for now: a "
                "tensor-parallel engine's pools are head-sharded and "
                "the batched extract/inject path moves whole pages — "
                "drop host_tier_bytes or drop mesh/sharding")
        if host_tier_bytes and draft_model is not None:
            raise ValueError(
                "a speculative engine cannot spill/restore prefix "
                "pages: a restored page carries only target-model KV, "
                "and the draft pool (addressed by the same page ids) "
                "cannot be reconstructed from it — drop "
                "host_tier_bytes or drop draft_model")
        if enable_prefix_cache:
            if not buckets and self.mixed is None:
                raise ValueError(
                    "enable_prefix_cache requires bucketed prefill "
                    "(prefill_buckets='auto'/tuple) or mixed_step=True: "
                    "suffix-only prefill needs an offset-carrying "
                    "compiled step")
            from .prefix_cache import HostPageTier, PrefixPageCache
            self.host_tier = (HostPageTier(int(host_tier_bytes))
                              if host_tier_bytes else None)
            self.prefix_cache = PrefixPageCache(
                self.caches[0], block_size, all_caches=self.caches,
                host_tier=self.host_tier)
        else:
            self.host_tier = None
            self.prefix_cache = None
        # published-so-far snapshot of the prefix cache's host-side
        # stat counters (evictions by outcome, spills/hits/restores);
        # _sync_prefix_stats diffs against it so the process-wide
        # metric counters see each increment exactly once
        self._pc_published: Dict[str, int] = {}
        self._chunk_rr = 0           # round-robin cursor over chunk work

        from ..observability import default_registry
        from ..observability.request_trace import resolve_tracer
        # bounded per-request phase tracer (round 16): typed spans for
        # admission, per-chunk prefill, sampled decode steps, first
        # token, preempt and finish — host-side appends only, keyed by
        # this engine's req_ids (a router merges them fleet-wide via
        # fleet_trace).  Default ON; tracer=False is the no-op stub.
        self.tracer = resolve_tracer(tracer)
        # decode spans are SAMPLED (every Nth step per request) so a
        # long generation neither floods the trace nor hits the
        # per-request event cap
        self.trace_decode_every = 8
        r = default_registry()
        self._m_queue = r.gauge(
            "serving_queue_depth", "requests waiting for a free slot")
        self._m_occupancy = r.gauge(
            "serving_slot_occupancy_ratio",
            "running slots / max_batch_size")
        self._m_kv_util = r.gauge(
            "serving_kv_page_utilization_ratio",
            "allocated KV pages / pool size")
        self._m_prefill = r.histogram(
            "serving_prefill_duration_seconds",
            "prompt prefill (bucketed compiled chunk, or the legacy "
            "dense forward + fused cache scatter)")
        self._m_decode = r.histogram(
            "serving_decode_step_duration_seconds",
            "one fused batched decode step (all slots)")
        self._m_ttft = r.histogram(
            "serving_ttft_seconds", "admission wait + prefill to first "
            "token (time-to-first-token)")
        self._m_tpot = r.histogram(
            "serving_tpot_seconds",
            "mean per-token decode latency after the first token",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0))
        self._m_requests = r.counter(
            "serving_requests_total", "finished generation requests",
            labels=("outcome",))
        self._m_tokens = r.counter(
            "serving_tokens_total", "tokens generated")
        self._m_truncated = r.counter(
            "serving_truncated_victims_total",
            "requests finished early because the KV pool ran dry "
            "(lazy_alloc victim contract)")
        self._m_prefill_compiles = r.counter(
            "serving_prefill_compiles_total",
            "bucketed PrefillStep traces (bounded by the bucket count)")
        self._m_prefix_lookups = r.counter(
            "serving_prefix_cache_lookups_total",
            "prompt admissions checked against the prefix table",
            labels=("outcome",))
        self._m_prefix_hit_tokens = r.counter(
            "serving_prefix_cache_hit_tokens_total",
            "prompt tokens served from shared prefix pages instead of "
            "recompute")
        self._m_prefix_evictions = r.counter(
            "serving_prefix_cache_evictions_total",
            "prefix table entries visited by eviction under pool "
            "pressure, by outcome (reclaimed = page returned to the "
            "free list, spilled first when a host tier is attached; "
            "skipped_pinned = a live request still holds the page, so "
            "the entry was passed over — sustained skips explain "
            "cache-pressure stalls)", labels=("outcome",))
        self._m_evict_reclaimed = \
            self._m_prefix_evictions.labels(outcome="reclaimed")
        self._m_evict_skipped = \
            self._m_prefix_evictions.labels(outcome="skipped_pinned")
        self._m_migrations = r.counter(
            "serving_page_migrations_total",
            "KV page-set migrations through this engine, by direction "
            "(out = extract_request serialized a sequence's pages to "
            "host; in = inject_request scattered a migrated buffer "
            "into this pool)", labels=("direction",))
        self._m_migrations_out = \
            self._m_migrations.labels(direction="out")
        self._m_migrations_in = self._m_migrations.labels(direction="in")
        self._m_migrated_bytes = r.counter(
            "serving_migrated_bytes_total",
            "payload bytes moved across the host link by page "
            "migration (each migration counts its buffer once on "
            "extract — device-to-host — and once on inject — "
            "host-to-device)")
        self._m_host_spills = r.counter(
            "serving_host_tier_spills_total",
            "evicted prefix pages serialized into the host-RAM spill "
            "tier instead of dying")
        self._m_host_hits = r.counter(
            "serving_host_tier_hits_total",
            "prefix lookups whose chain continued into the host tier "
            "(spilled pages found for the prompt)")
        self._m_host_restores = r.counter(
            "serving_host_tier_restores_total",
            "spilled pages injected back into the device pool and "
            "re-registered under their digest keys")
        self._m_chunk_queue = r.gauge(
            "serving_prefill_chunk_queue_depth",
            "prefill chunks still pending across admitted requests")
        self._m_mixed_compiles = r.counter(
            "serving_mixed_step_compiles_total",
            "fused MixedStep traces (bounded by the token-budget-set "
            "size)")
        self._m_mixed_span_tokens = r.counter(
            "serving_mixed_span_tokens_total",
            "tokens advanced by the fused mixed step, by span kind",
            labels=("kind",))
        # resolve the labeled children ONCE: .labels() is a lock + dict
        # probe, and the mixed step pays it every engine round
        self._m_mixed_tok_decode = \
            self._m_mixed_span_tokens.labels(kind="decode")
        self._m_mixed_tok_prefill = \
            self._m_mixed_span_tokens.labels(kind="prefill")
        self._m_tp_degree = r.gauge(
            "serving_tp_degree",
            "tensor-parallel degree of the most recently constructed "
            "engine in this process (1 = single chip)")
        self._m_tp_degree.set(self.tp_degree)
        self._m_tp_collective = r.counter(
            "serving_tp_collective_bytes_total",
            "per-chip activation bytes moved through the sharded "
            "step's collectives (psum per layer boundary, exact "
            "embedding psum, exact logits all-gather)", labels=("op",))
        self._m_tp_psum = self._m_tp_collective.labels(op="psum")
        self._m_tp_all_gather = \
            self._m_tp_collective.labels(op="all_gather")
        # 2D serving mesh (round 21): per-axis shape of the most
        # recently constructed engine's mesh — fsdp (weight storage),
        # tp (compute), dp (replica); 1 = the axis is absent
        self._m_mesh_shape = r.gauge(
            "serving_mesh_shape",
            "serving mesh degree per axis for the most recently "
            "constructed engine (fsdp = weight-storage sharding, tp = "
            "tensor parallel, dp = replica) — 1 means the axis is "
            "absent", labels=("axis",))
        mesh_sizes = dict(self.tp.mesh.shape) if self.tp is not None \
            else {}
        self._m_mesh_shape.labels(axis="fsdp").set(
            self.fsdp_degree)
        self._m_mesh_shape.labels(axis="tp").set(self.tp_degree)
        self._m_mesh_shape.labels(axis="dp").set(
            int(mesh_sizes.get("dp", 1)))
        self._m_mesh_shape.labels(axis="cp").set(self.cp_degree)
        self._m_mesh_shape.labels(axis="ep").set(self.ep_degree)
        # context-parallel serving (round 22): pool-stripe degree and
        # the stripe-merge collective payload
        self._m_cp_degree = r.gauge(
            "serving_cp_degree",
            "context-parallel degree of the most recently constructed "
            "engine (cp stripes every KV pool's slot dim — per-chip "
            "pool HBM is 1/cp; 1 = pools not striped)")
        self._m_cp_degree.set(self.cp_degree)
        self._m_cp_collective = r.counter(
            "serving_cp_collective_bytes_total",
            "per-chip bytes received by the cross-chip online-softmax "
            "stripe merge (one all_gather of the (o, m, l) partial "
            "rows per layer per sharded dispatch)", labels=("op",))
        self._m_cp_all_gather = \
            self._m_cp_collective.labels(op="all_gather")
        # expert-parallel MoE serving (round 24): expert-bank shard
        # degree and the dispatch/combine payloads of the fused step
        self._m_ep_degree = r.gauge(
            "serving_ep_degree",
            "expert-parallel degree of the most recently constructed "
            "engine (ep shards every MoE expert bank's E dim — "
            "per-chip expert HBM is 1/ep; 1 = expert banks replicated)")
        self._m_ep_degree.set(self.ep_degree)
        self._m_moe_dispatch = r.counter(
            "serving_moe_dispatch_tokens_total",
            "token->expert assignments made by the fused MoE serving "
            "dispatch (tokens x top_k x MoE layers), by fate — the "
            "dispatch is DROPLESS (capacity == worst-case load), so "
            "'dropped' stays 0 by construction and a nonzero value "
            "means the capacity invariant broke", labels=("fate",))
        self._m_moe_routed = self._m_moe_dispatch.labels(fate="routed")
        # resolve the 'dropped' child eagerly so /metrics always shows
        # the 0 that documents droplessness
        self._m_moe_dropped = self._m_moe_dispatch.labels(fate="dropped")
        self._m_ep_collective = r.counter(
            "serving_ep_collective_bytes_total",
            "per-chip bytes moved by the expert-parallel dispatch "
            "(all_to_all = the send/return buffer pair per MoE layer, "
            "all_gather = re-replicating the combined token stripes)",
            labels=("op",))
        self._m_ep_all_to_all = \
            self._m_ep_collective.labels(op="all_to_all")
        self._m_ep_all_gather = \
            self._m_ep_collective.labels(op="all_gather")
        self._m_fsdp_gather = r.counter(
            "spmd_allgather_bytes_total",
            "per-chip bytes received by spmd param all-gathers, by "
            "site: the 2D train step's per-step param gather "
            "(train_params) and the sharded serving prologue's fsdp "
            "gather (serving_params)", labels=("site",)
        ).labels(site="serving_params")
        # static per-dispatch payload of the prologue's fsdp param
        # gather (0 without an fsdp axis) — counted per sharded
        # dispatch next to the activation collectives
        if self.tp is not None:
            tree = self.weight_qtree if self.weight_qtree is not None \
                else {k: t._value for k, t in model.state_dict().items()}
            self._fsdp_gather_bytes = self.tp.fsdp_gather_bytes(tree)
        else:
            self._fsdp_gather_bytes = 0
        self._m_kv_quant_dtype = r.gauge(
            "serving_kv_quant_dtype",
            "KV-cache element width in bits of the most recently "
            "constructed engine (8 = int8 quantized pools, 16/32 = fp)")
        # read the CONSTRUCTED pool's dtype (kv_dtype may explicitly
        # override the model dtype, e.g. bfloat16 pools under fp32)
        self._m_kv_quant_dtype.set(
            self.caches[0].key_cache.dtype.itemsize * 8)
        self._m_quant_collective = r.counter(
            "serving_quant_collective_bytes_total",
            "per-chip bytes moved through QUANTIZED collectives (the "
            "EQuARX-style int8 logits all-gather: codes + per-shard "
            "scales)", labels=("op",))
        self._m_quant_all_gather = \
            self._m_quant_collective.labels(op="all_gather")
        self._m_quant_mismatch = r.counter(
            "serving_quant_token_mismatch_total",
            "greedy tokens that diverged from the fp32 reference "
            "engine on a paired run (published by the quantization "
            "bench/tests via record_token_mismatches — the tolerance "
            "gate's numerator)")
        self._m_sampling_mode = r.gauge(
            "serving_sampling_mode",
            "1 = the stochastic sampling epilogue is compiled into "
            "this process's most recently constructed engine, 0 = "
            "greedy-only")
        self._m_sampling_mode.set(1 if self.sampling else 0)
        self._m_spec_proposed = r.counter(
            "serving_spec_proposed_tokens_total",
            "draft tokens proposed to the speculative verifier")
        self._m_spec_accepted = r.counter(
            "serving_spec_accepted_tokens_total",
            "proposed draft tokens the target verifier accepted "
            "(acceptance rate = accepted / proposed)")
        self._m_draft_step = r.histogram(
            "serving_spec_draft_step_duration_seconds",
            "one fused draft-model launch (catch-up + proposal or "
            "chunk mirror; compile warmup excluded)",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0))
        # compile warmup never lands in a latency histogram.  Bucketed
        # prefill tracks warmth PER BUCKET via the step's own compile
        # counters (a call that traced is cold, everything else is warm
        # — chunk offset and raw prompt length don't retrace).  The
        # legacy dense path re-traces per prompt length, so its warmth
        # stays per-length.
        self._prefill_warm_lens = set()
        self._decode_warm = False
        # step()-scoped collection of requests _finish'd during
        # admission/prefill (None outside a step: direct _admit calls,
        # e.g. benches, skip it)
        self._finished_this_step = None
        # per-ENGINE cumulative host counters (round 20): the
        # process-wide prometheus counters aggregate across every
        # engine in the process, so the capacity plane's per-engine
        # windowed rates read THESE off health_payload instead
        self.counters: Dict[str, int] = {
            "tokens_generated": 0, "requests_received": 0,
            "requests_admitted": 0, "preempts": 0}
        # lazily computed serving-step cost_analysis block (round 20
        # capacity plane); stays None until efficiency_stats(
        # compute=True) runs — a health scrape must never compile —
        # and a FAILED probe latches too (one compile attempt ever)
        self._efficiency_stats: Optional[Dict] = None
        self._efficiency_failed = False

    @staticmethod
    def _auto_buckets(max_seq_len: int):
        """Geometric 32/64/.../top, top = pow2 ceil of max_seq_len
        capped at 512 (longer prompts prefill in chunks of the top
        bucket)."""
        top = 1
        while top < max_seq_len:
            top *= 2
        top = min(top, 512)
        out = []
        b = 32
        while b < top:
            out.append(b)
            b *= 2
        out.append(top)
        return tuple(sorted({x for x in out if x <= top}))

    @staticmethod
    def _auto_budgets_mixed(slots: int, chunk: int):
        """Geometric total-token budgets for the mixed step: from the
        pow2 ceil of the slot count (the all-decode pack) doubling up
        past slots + chunk (every slot decoding while a full prefill
        chunk rides along)."""
        b = 1
        while b < max(1, slots):
            b *= 2
        out = [b]
        while b < slots + chunk:
            b *= 2
            out.append(b)
        return tuple(out)

    # ---- public API ----------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=16,
                    eos_token_id=None, temperature: float = 0.0,
                    top_k: int = 0, top_p: float = 0.0, seed: int = 0,
                    n: int = 1):
        """Queue one prompt.  ``temperature``/``top_k``/``top_p``/
        ``seed`` select stochastic sampling (engine must be built with
        ``sampling=True``; temperature 0 = greedy).  ``n>1`` queues n
        generations of the SAME prompt that share one prefilled prefix
        through the copy-on-write prefix-page machinery (requires
        ``enable_prefix_cache=True``): generation i samples with
        ``seed + i``, children admit only after the first generation's
        prefill publishes the shared pages (ref++ on every shared
        page, per-generation divergent suffixes).  Returns the req_id,
        or the list of n req_ids when ``n > 1``."""
        if (temperature or top_k or top_p or seed) and not self.sampling:
            raise ValueError(
                "per-request sampling parameters need a sampling "
                "engine: construct ContinuousBatchingEngine("
                "sampling=True, ...) — the greedy engine's compiled "
                "steps have no sampling epilogue")
        if n < 1:
            raise ValueError("add_request n must be >= 1, got %r" % n)
        if n > 1 and self.prefix_cache is None:
            raise ValueError(
                "add_request(n=%d) shares one prefilled prefix across "
                "generations via the prefix-page cache: construct the "
                "engine with enable_prefix_cache=True" % n)
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        need = self.caches[0].blocks_needed(len(prompt) + max_new_tokens)
        if need > self.bt_width:
            raise ValueError(
                "request needs %d pages but the engine's block-table "
                "width is %d (max_seq_len=%d); raise max_seq_len"
                % (need, self.bt_width, self.max_seq_len))
        min_need = need if not self.lazy_alloc else \
            self.caches[0].blocks_needed(len(prompt) + 1)
        if min_need > self.caches[0].num_blocks:
            # would never admit: _admit waits for pages that can't exist
            # (lazy mode only needs the prompt to fit — the tail may be
            # truncated if the pool runs dry)
            raise ValueError(
                "request needs %d pages but the pool only has %d; "
                "raise num_blocks" % (min_need, self.caches[0].num_blocks))
        ids = []
        parent = None
        for i in range(n):
            req = GenerationRequest(
                req_id=self._next_id, prompt_ids=prompt,
                max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
                temperature=float(temperature), top_k=int(top_k),
                top_p=float(top_p), seed=int(seed) + i,
                parent_req=parent)
            if parent is None:
                parent = req
            self._next_id += 1
            req.t_submit = time.perf_counter()
            self.waiting.append(req)
            ids.append(req.req_id)
        self.counters["requests_received"] += n
        self._m_queue.set(len(self.waiting))
        return ids[0] if n == 1 else ids

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None
                                         for s in self.slots)

    def step(self) -> List[int]:
        """Admit waiting requests, then advance the engine one round:
        mixed mode packs every running slot's decode token AND as many
        pending prefill chunks as the token budget holds into one fused
        launch; the split mode advances at most one prefill chunk, then
        decodes every running slot.  Returns req_ids finished this
        step — including requests that completed DURING admission
        (a one-token budget or EOS on the first sampled token ends a
        request inside the prefill itself; multi-engine callers key on
        the returned ids, so those must not go missing)."""
        self._finished_this_step = fts = []
        try:
            self._admit()
            if self.mixed is not None:
                done = self._run_mixed_step()
            else:
                self._prefill_chunks()
                done = self._decode_batch()
        finally:
            # restore the documented outside-a-step invariant (None)
            # even on a raising step, so direct _admit/_finish callers
            # between steps don't feed a stale list
            self._finished_this_step = None
        seen = set(done)
        done += [rid for rid in fts if rid not in seen]
        self._m_queue.set(len(self.waiting))
        self._m_occupancy.set(
            sum(s is not None for s in self.slots)
            / max(1, self.max_batch_size))
        cache = self.caches[0]
        self._m_kv_util.set(
            1.0 - len(cache._free) / max(1, cache.num_blocks))
        if self.chunk_size is not None:
            # mixed chunks no longer consume a dedicated engine round,
            # but the backlog gauge still reports what is pending
            self._m_chunk_queue.set(self._pending_chunks())
        self._sync_prefix_stats()
        return done

    def run_to_completion(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        return {rid: r.output_ids for rid, r in self.finished.items()}

    def result(self, req_id: int) -> List[int]:
        return self.finished[req_id].output_ids

    def preempt_request(self, req_id: int) -> Tuple[np.ndarray, List[int]]:
        """Pull a waiting or running request OUT of the engine and
        return ``(prompt_ids, generated_ids)`` so an admission plane can
        re-admit it elsewhere (preempt-and-requeue: the request resumes
        on another engine with its generated tokens re-prefixed onto the
        prompt — NOT the lazy-alloc victim-truncation path, which ends a
        request early).

        A running slot is released through the refcounted
        ``free_sequence`` path — the ONLY release path — so pages shared
        with the prefix table or another live request survive, COW
        copies return to the pool, and an int8 pool's per-page scale
        rows stay consistent (scales live per PHYSICAL page and carry no
        per-request state).  The request is NOT finished: no outcome
        counter fires, nothing lands in ``finished``.  Raises KeyError
        when ``req_id`` is neither waiting nor on a slot (already
        finished requests are not preemptible)."""
        for i, r in enumerate(self.waiting):
            if r.req_id == req_id:
                self.waiting.pop(i)
                self._m_queue.set(len(self.waiting))
                r.state = "preempted"
                self.counters["preempts"] += 1
                self.tracer.event(req_id, "preempt", from_state="waiting",
                                  tokens=len(r.output_ids))
                return r.prompt_ids, list(r.output_ids)
        for r in self.slots:
            if r is None or r.req_id != req_id:
                continue
            self._release_slot(r)
            r.slot = -1
            r.state = "preempted"
            self.counters["preempts"] += 1
            self.tracer.event(req_id, "preempt", from_state="running",
                              tokens=len(r.output_ids))
            return r.prompt_ids, list(r.output_ids)
        raise KeyError(
            "preempt_request(%r): request is neither waiting nor "
            "running on this engine" % (req_id,))

    # ---- KV page migration (round 19) -----------------------------------
    def migration_geometry(self):
        """The pool geometry ``(layers, block_size, kv_heads, head_dim,
        kv_dtype)`` page buffers extracted from / injected into this
        engine must match — or None when this engine cannot migrate
        pages at all (tensor-parallel pools are head-sharded; a
        speculative engine's draft KV cannot travel).  Admission planes
        pre-check this so they never extract a buffer no target can
        take (a failed migration degrades to paying the prefill
        twice)."""
        if self.tp is not None or self.draft_step is not None:
            return None
        return (len(self.caches),) + self.caches[0].page_geometry()

    def extract_request(self, req_id: int):
        """``preempt_request`` plus page extraction: pull the request
        out AND serialize its KV pages to one host
        :class:`~paddle_tpu.ops.paged_attention.KVPageBuffer` (one
        batched device→host copy per dtype) BEFORE the refcounted
        release, so an admission plane can resume it on another engine
        with ZERO re-prefill (``inject_request``).  Returns
        ``(prompt_ids, generated_ids, buffer)``; ``buffer`` is None
        when the request holds no resumable KV (still waiting, or
        mid-prefill) or when this engine cannot extract (tensor-
        parallel pools are head-sharded; a speculative engine's draft
        KV cannot travel) — the caller then falls back to the r15
        re-prefill resume."""
        buf = None
        if self.migration_geometry() is not None:
            for r in self.slots:
                if (r is not None and r.req_id == req_id
                        and r.state == "running" and r.seq_len > 0):
                    from ..jit.serving_step import extract_blocks
                    n_cov = self.caches[0].blocks_needed(r.seq_len)
                    buf = extract_blocks(self.caches,
                                         r.block_ids[:n_cov],
                                         n_tokens=r.seq_len)
                    break
        prompt, gen = self.preempt_request(req_id)
        if buf is not None:
            self._m_migrations_out.inc()
            self._m_migrated_bytes.inc(buf.nbytes)
        return prompt, gen, buf

    def inject_request(self, prompt_ids, buffer, max_new_tokens=16,
                       eos_token_id=None, temperature: float = 0.0,
                       top_k: int = 0, top_p: float = 0.0,
                       seed: int = 0) -> int:
        """Admit a MIGRATED request straight into a decode slot: the
        buffer's pages scatter into freshly allocated pool pages in ONE
        donated dispatch, the request starts in state "running" with
        its last prompt token pending, and the next engine step
        advances it as a plain decode span — zero re-prefill.  The
        covered full pages re-register under the same blake2b digest
        chain the prefix cache keys on, so affinity and COW sharing
        work on the target exactly as if it had prefilled the prompt
        itself.

        ``prompt_ids`` is the RESUME prompt (original prompt plus every
        token already generated); ``buffer.n_tokens`` must equal
        ``len(prompt_ids) - 1`` — the KV of everything but the last
        token, whose forward pass produces the next one.

        The buffer carries KV, NOT sampling state: a stochastic
        request must re-pass its ``temperature``/``top_k``/``top_p``/
        ``seed`` here (exactly ``add_request``'s contract — defaults
        are greedy).  The r14 counter-based PRNG keys on (seed, token
        position), so a re-seeded migrated stream samples the same
        distribution path it would have on the source engine.

        Raises ``ValueError`` for a request this engine can never hold
        (geometry/kv_dtype mismatch, block-table width) and
        ``RuntimeError`` for transient capacity (no free slot, pool
        cannot cover the pages) — both BEFORE any side effect, so the
        caller can fall back to ``add_request`` (re-prefill resume)."""
        if buffer is None:
            raise ValueError(
                "inject_request needs a KVPageBuffer — use add_request "
                "for a fresh (un-migrated) prompt")
        if self.tp is not None:
            raise ValueError(
                "page migration is single-chip for now: a tensor-"
                "parallel engine's pools are head-sharded and the "
                "batched inject moves whole pages")
        if self.draft_step is not None:
            raise ValueError(
                "a speculative engine cannot accept migrated pages: "
                "the buffer carries only target-model KV and the draft "
                "pool (addressed by the same page ids) cannot be "
                "reconstructed from it")
        here = (len(self.caches),) + self.caches[0].page_geometry()
        if here != buffer.geometry():
            raise ValueError(
                "inject_request: pool geometry mismatch — buffer was "
                "extracted from (layers, block_size, kv_heads, "
                "head_dim, kv_dtype)=%r but this engine's pools are "
                "%r; KV pages only migrate between engines with "
                "identical pool geometry (including kv_dtype)"
                % (buffer.geometry(), here))
        if int(max_new_tokens) < 1:
            raise ValueError(
                "inject_request max_new_tokens must be >= 1; a "
                "migrated request with no remaining budget should "
                "complete at the router, not resume")
        if (temperature or top_k or top_p or seed) and not self.sampling:
            raise ValueError(
                "per-request sampling parameters need a sampling "
                "engine: construct ContinuousBatchingEngine("
                "sampling=True, ...) — the greedy engine's compiled "
                "steps have no sampling epilogue")
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        L = len(prompt)
        if buffer.n_tokens != L - 1:
            raise ValueError(
                "inject_request: buffer covers %d token(s) of KV but "
                "the resume prompt has %d — a migrated request resumes "
                "with exactly its last token pending (n_tokens == "
                "len(prompt_ids) - 1)" % (buffer.n_tokens, L))
        cache = self.caches[0]
        n_cov = cache.blocks_needed(buffer.n_tokens)
        if buffer.n_pages != n_cov:
            raise ValueError(
                "inject_request: buffer holds %d page(s) but %d cover "
                "its %d token(s) at block_size=%d"
                % (buffer.n_pages, n_cov, buffer.n_tokens,
                   self.block_size))
        total_need = cache.blocks_needed(
            L + (1 if self.lazy_alloc else int(max_new_tokens)))
        if total_need > self.bt_width:
            raise ValueError(
                "request needs %d pages but the engine's block-table "
                "width is %d (max_seq_len=%d); raise max_seq_len"
                % (total_need, self.bt_width, self.max_seq_len))
        slot = next((i for i, s in enumerate(self.slots) if s is None),
                    None)
        if slot is None:
            raise RuntimeError(
                "inject_request: no free slot — inject only into "
                "engines with slot capacity (migrated requests do not "
                "queue; their pages would pin pool pages while "
                "waiting)")
        available = len(cache._free)
        if self.prefix_cache is not None:
            available += self.prefix_cache.evictable_count()
        if total_need > available:
            raise RuntimeError(
                "inject_request: pool cannot cover %d page(s) "
                "(%d free + %d evictable)"
                % (total_need, len(cache._free), available
                   - len(cache._free)))

        # ---- commit ---------------------------------------------------
        from ..jit.serving_step import inject_blocks
        # one batched spill for the whole deficit (see _try_admit)
        short = total_need - len(cache._free)
        if short > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict(short)
            self._sync_prefix_stats()
        req = GenerationRequest(
            req_id=self._next_id, prompt_ids=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id,
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p), seed=int(seed))
        self._next_id += 1
        req.t_submit = time.perf_counter()
        req.block_ids = [self._alloc_block() for _ in range(total_need)]
        inject_blocks(self.caches, buffer, req.block_ids[:n_cov])
        req.slot = slot
        req.state = "running"
        req.seq_len = buffer.n_tokens
        req.prefill_pos = L
        req.prefix_hit_tokens = 0
        self.slots[slot] = req
        self._tokens[slot] = int(prompt[-1])
        self._seq_lens[slot] = req.seq_len
        self._bt[slot] = self._row_for(req)[0]
        if self.sampling:
            self._samp[slot] = self._samp_row(req)
        if self.prefix_cache is not None:
            # re-register the COVERED full pages under the same digest
            # chain (truncate the prompt to them: pages past n_tokens
            # hold no KV yet and must not be published)
            full = (buffer.n_tokens // self.block_size) * self.block_size
            if full:
                self.prefix_cache.register(prompt[:full], req.block_ids)
        self._m_migrations_in.inc()
        self._m_migrated_bytes.inc(buffer.nbytes)
        self.counters["requests_received"] += 1
        self.counters["requests_admitted"] += 1
        self.tracer.event(req.req_id, "admit", slot=slot,
                          prefix_hit_tokens=0, prompt_tokens=L,
                          enqueue_ts=req.t_submit, migrated=True)
        return req.req_id

    def health_payload(self) -> Dict[str, int]:
        """Load/health snapshot for admission planes: the same stats
        the observability gauges read (occupancy, KV-page utilization,
        chunk-queue depth), as one host-side dict — the body
        ``/healthz`` serves when this engine is installed as the
        process's health provider (``observability.set_health_provider(
        engine.health_payload)``), so a router scrapes load without
        parsing Prometheus text.

        Round 20: the payload also carries ``counters`` — this
        engine's cumulative host-side counts (tokens, admissions,
        preempts, prefix lookups/hits, host-tier spills/restores) —
        which the capacity plane's ``SignalWindow``\\ s turn into
        rolling rates and drifts, and ``efficiency`` once (and only
        once) ``efficiency_stats(compute=True)`` has run — a health
        scrape itself never triggers a compile."""
        pc = self.prefix_cache
        cache = self.caches[0]
        payload = {
            "engine_id": self.engine_id,
            "role": self.role,
            "occupancy": sum(s is not None for s in self.slots),
            "slots": self.max_batch_size,
            "waiting": len(self.waiting),
            "free_pages": len(cache._free),
            "total_pages": cache.num_blocks,
            "chunk_queue_depth": (self._pending_chunks()
                                  if self.chunk_size is not None else 0),
            # round 20: pages the prefix cache could reclaim RIGHT NOW
            # (table entries no live request holds) — the capacity
            # plane's saturation must not read a cache-warm idle
            # engine as full (those pages free under pressure)
            "evictable_pages": (pc.evictable_count()
                                if pc is not None else 0),
            # round 19: the host spill tier's footprint rides the same
            # payload the router's load_score and the r16 SLO plane
            # already scrape — no extra endpoint
            "host_tier_bytes": (self.host_tier.bytes
                                if self.host_tier is not None else 0),
            "host_tier_entries": (len(self.host_tier)
                                  if self.host_tier is not None else 0),
        }
        payload["counters"] = {
            **self.counters,
            "prefix_lookups": (pc.hits + pc.misses) if pc is not None
            else 0,
            "prefix_hits": pc.hits if pc is not None else 0,
            "host_tier_spills": pc.spills if pc is not None else 0,
            "host_tier_restores": pc.restores if pc is not None else 0,
        }
        if self._efficiency_stats is not None:
            payload["efficiency"] = self._efficiency_stats
        return payload

    def efficiency_stats(self, compute: bool = False) -> Optional[Dict]:
        """Serving-step device-efficiency numbers off the COMPILED
        step's ``cost_analysis`` — the serving twin of the round-9
        train MFU probe, with the same contract: lazy, cached for the
        engine's lifetime, one extra AOT compile ever, opt out with
        ``PADDLE_TPU_MFU_COST_ANALYSIS=0`` (tests/conftest.py sets it,
        so the tier-1 budget never pays this).  ``compute=False`` (the
        health-payload read) returns the cached block or None — it
        NEVER compiles.

        The probed launch is the engine's steady-state decode shape:
        the SMALLEST mixed token budget (an all-decode pack fits it)
        or the split decode step at the slot count.  Per-token numbers
        amortize over the launch's packed token capacity — padding
        spans do sink-page work the device genuinely executes.  The
        numbers describe the compiled XLA module, which on CPU is the
        XLA reference attention, not the interpret-mode Pallas kernel
        (BASELINE round-17 honesty note)."""
        if self._efficiency_stats is not None:
            return self._efficiency_stats
        if not compute:
            return None
        if self._efficiency_failed:
            # a failed probe is cached too — the 'one extra AOT
            # compile ever' contract also covers the failure path (a
            # periodic refresh must not re-pay a multi-second failing
            # compile every sweep); the env gate is NOT a failure
            return None
        from ..observability.capacity import _cost_analysis_enabled
        if not _cost_analysis_enabled():
            return None
        try:
            if self.mixed is not None:
                # the steady-state all-decode launch shape: the
                # SMALLEST budget an all-decode pack fits (explicit
                # budget sets only validate their TOP against it, so
                # budgets[0] can be far smaller — probing it would
                # amortize the weights over too few tokens and inflate
                # the per-token numbers)
                base = self.max_batch_size * (self.spec_k + 1)
                T = min((b for b in self.token_budgets if b >= base),
                        default=self.token_budgets[-1])
                stats = self.mixed.compiled_stats(T)
                kind = "mixed"
            else:
                stats = self.decode_step.compiled_stats(
                    self.max_batch_size)
                kind = "decode"
        except Exception:                             # noqa: BLE001
            self._efficiency_failed = True
            return None
        if not stats.get("flops_per_token"):
            self._efficiency_failed = True
            return None
        self._efficiency_stats = {
            "step": kind,
            "tokens_per_launch": int(stats["tokens"]),
            "flops_per_token": float(stats["flops_per_token"]),
            "hbm_bytes_per_token": float(
                stats.get("hbm_bytes_per_token", 0.0)),
            "flops_per_launch": float(stats.get("flops", 0.0)),
            "source": "cost_analysis",
        }
        return self._efficiency_stats

    # ---- page allocation ------------------------------------------------
    def _try_alloc(self) -> Optional[int]:
        """Pop a free page, reclaiming unreferenced prefix-cache pages
        under pressure (eviction honors refcounts: only table entries
        no live request holds are dropped)."""
        c = self.caches[0]
        if not c._free and self.prefix_cache is not None:
            self.prefix_cache.evict(1)
            self._sync_prefix_stats()
        if not c._free:
            return None
        return c.allocate_block()

    def _sync_prefix_stats(self):
        """Publish the prefix cache's host-side stat counters (evictions
        by outcome, host-tier spills/hits/restores) into the
        process-wide metrics — diffed against the last published
        snapshot so every increment lands exactly once."""
        pc = self.prefix_cache
        if pc is None:
            return
        pub = self._pc_published
        for attr, metric in (
                ("evictions", self._m_evict_reclaimed),
                ("skipped_pinned", self._m_evict_skipped),
                ("spills", self._m_host_spills),
                ("host_hits", self._m_host_hits),
                ("restores", self._m_host_restores)):
            cur = getattr(pc, attr)
            delta = cur - pub.get(attr, 0)
            if delta:
                metric.inc(delta)
                pub[attr] = cur

    def _alloc_block(self) -> int:
        blk = self._try_alloc()
        if blk is None:
            raise RuntimeError(
                "PagedKVCache out of blocks (%d in pool) and nothing "
                "evictable" % self.caches[0].num_blocks)
        return blk

    def _row_for(self, req: GenerationRequest) -> np.ndarray:
        row = np.full((1, self.bt_width), self._sink, np.int32)
        row[0, :len(req.block_ids)] = req.block_ids
        return row

    # ---- admission (prefill) -------------------------------------------
    def _admit(self):
        for i in range(self.max_batch_size):
            if not self.waiting or self.slots[i] is not None:
                continue
            if not self._try_admit(self.waiting[0], i):
                break                   # no room yet: keep waiting (FIFO)
            self.waiting.pop(0)

    def _try_admit(self, req: GenerationRequest, slot: int) -> bool:
        """Match the prompt against the prefix cache, reserve pages,
        and start (or finish) the suffix prefill.  Returns False —
        with NO side effects — when the pool cannot cover the request
        yet."""
        if req.parent_req is not None \
                and req.parent_req.state in ("waiting", "prefilling"):
            # n>1 group: wait for the parent generation's prefill to
            # publish the shared prefix pages, so this child admits as
            # a whole-prompt hit (ref++ + COW) instead of recomputing
            return False
        cache = self.caches[0]
        L = len(req.prompt_ids)
        matched: List[int] = []
        hit_len = 0
        cow = False
        if self.prefix_cache is not None:
            matched = self.prefix_cache.match(req.prompt_ids)
            hit_len = len(matched) * self.block_size
            if matched and hit_len >= L:
                # whole-prompt hit: re-run the last position to sample
                # the first token — the suffix write lands mid-page in
                # the final shared block, which therefore needs a
                # private copy (copy-on-write on the first partial page)
                hit_len = L - 1
                cow = True
        total_need = cache.blocks_needed(
            L + (1 if self.lazy_alloc else req.max_new_tokens))
        new_needed = total_need - len(matched) + (1 if cow else 0)
        available = len(cache._free)
        if self.prefix_cache is not None:
            available += self.prefix_cache.evictable_count(
                exclude=set(matched))
        if new_needed > available:
            return False

        # ---- commit ---------------------------------------------------
        if self.prefix_cache is not None:
            # literal label values: the metric lint pins label domains
            self._m_prefix_lookups.labels(
                outcome="hit" if matched else "miss").inc()
            if matched:
                self.prefix_cache.hits += 1
                self.prefix_cache.hit_tokens += hit_len
                self._m_prefix_hit_tokens.inc(hit_len)
            else:
                self.prefix_cache.misses += 1
        cache.share_blocks(matched)
        req.block_ids = list(matched)
        # evict the whole page deficit UP FRONT: one evict() call
        # spills every victim in ONE batched extract (the r11
        # transfer-count rule) — _alloc_block's evict(1) stays only as
        # the safety net.  Safe only AFTER share_blocks: the matched
        # pages now hold a second reference, so eviction skips them
        short = new_needed - len(cache._free)
        if short > 0 and self.prefix_cache is not None:
            self.prefix_cache.evict(short)
            self._sync_prefix_stats()
        if cow:
            from ..jit.serving_step import copy_block
            src = req.block_ids[-1]
            dst = self._alloc_block()
            copy_block(self.caches, src, dst)
            if self.draft_caches:
                # the draft pool shares page ids: its copy of the
                # shared page moves with the target's
                copy_block(self.draft_caches, src, dst)
            cache.free_sequence([src])      # drop this request's share
            req.block_ids[-1] = dst
        while len(req.block_ids) < total_need:
            req.block_ids.append(self._alloc_block())
        req.prefill_pos = hit_len
        req.prefix_hit_tokens = hit_len
        # a prefix hit fills the draft pool too (same page ids, written
        # by the publisher's mirrored chunks); the suffix chunks mirror
        # from prefill_pos on
        req.draft_len = hit_len
        req.slot = slot
        req.state = "prefilling"
        self.slots[slot] = req
        self.counters["requests_admitted"] += 1
        # ONE admission record (enqueue ts rides as an arg — the
        # tracer is on the admission path, so records are budgeted)
        self.tracer.event(req.req_id, "admit", slot=slot,
                          prefix_hit_tokens=hit_len,
                          prompt_tokens=L,
                          enqueue_ts=req.t_submit)
        if self.sampling:
            self._samp[slot] = self._samp_row(req)
        if self.mixed is not None:
            # chunks ride the fused mixed step packed this same step()
            # — admission never runs a separate prefill dispatch
            pass
        elif self.prefill_step is None:
            self._prefill_dense(req)
        elif L - hit_len <= self.chunk_size:
            # suffix fits one bucket: prefill at admission (short
            # prompts keep the old admit-then-decode-same-step timing)
            self._prefill_chunk(req)
        # else: long suffix — chunks advance one per step() interleaved
        # with decode (_prefill_chunks)
        return True

    # ---- legacy dense prefill (prefill_buckets=None) --------------------
    def _prefill_dense(self, req: GenerationRequest):
        """Run the whole prompt through the model's dense path once,
        scatter the per-layer K/V into cache pages with ONE fused call,
        sample the first token.  Re-traces per distinct prompt length —
        the bucketed path exists to bound exactly that."""
        import paddle_tpu as paddle
        from ..autograd.tape import no_grad
        from ..jit.serving_step import prefill_scatter
        t_prefill = time.perf_counter()
        L = len(req.prompt_ids)
        ids = paddle.to_tensor(req.prompt_ids[None, :].astype(np.int64))
        with no_grad():
            logits, kv = self.model.forward(
                ids, caches=[(None, None)] * self.cfg.num_hidden_layers)
        row = self._row_for(req)
        # k/v [1, L, Hkv, D] pre-GQA-repeat — one donated scatter over
        # ALL layers (not a Python loop of per-layer dispatches)
        prefill_scatter(self.caches, kv, row)
        # first-token sample: argmax of the last position ON DEVICE —
        # only one int32 scalar crosses the host link, never the
        # [1, V] (let alone [1, L, V]) logits
        first = int(jnp.argmax(
            logits._value[0, -1, :].astype(jnp.float32)))
        t_end = time.perf_counter()
        if L in self._prefill_warm_lens:
            self._m_prefill.observe(t_end - t_prefill)
        self._prefill_warm_lens.add(L)
        self.tracer.span(req.req_id, "prefill_dense", t_prefill, t_end,
                         tokens=L)
        req.prefill_pos = L
        self._complete_prefill(req, first, row)

    # ---- bucketed / chunked prefill -------------------------------------
    def _bucket_for(self, size: int) -> int:
        for b in self.prefill_buckets:
            if b >= size:
                return b
        raise AssertionError(
            "chunk of %d tokens exceeds the top bucket %d"
            % (size, self.prefill_buckets[-1]))

    def _pending_chunks(self) -> int:
        n = 0
        for r in self.slots:
            if r is not None and r.state == "prefilling":
                rem = len(r.prompt_ids) - r.prefill_pos
                n += -(-rem // self.chunk_size)
        return n

    def _prefill_chunks(self):
        """Advance AT MOST one pending prefill chunk (round-robin over
        slots): a long prompt pays its prefill one chunk per engine
        step, interleaved with decode, instead of stalling every
        running request's TPOT for its whole length."""
        if self.prefill_step is None:
            return
        n = self.max_batch_size
        for k in range(n):
            i = (self._chunk_rr + k) % n
            r = self.slots[i]
            if r is not None and r.state == "prefilling":
                self._prefill_chunk(r)
                self._chunk_rr = (i + 1) % n
                return

    def _prefill_chunk(self, req: GenerationRequest):
        """Run one bucket-padded chunk through the compiled PrefillStep;
        on the final chunk, complete admission with the on-device
        sampled first token."""
        L = len(req.prompt_ids)
        start = req.prefill_pos
        size = min(self.chunk_size, L - start)
        bucket = self._bucket_for(size)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :size] = req.prompt_ids[start:start + size]
        row = self._row_for(req)
        t0 = time.perf_counter()
        pre = self.prefill_step.total_compiles
        first = self.prefill_step(
            toks, start, size, row,
            self._samp_row(req) if self.sampling else None)
        traced = self.prefill_step.total_compiles - pre
        if self.tp is not None:
            self._count_collectives(
                self.prefill_step.collective_bytes(bucket))
        t_end = time.perf_counter()
        if traced:
            # first compile of this bucket: count it, keep the warmup
            # out of the latency histogram
            self._m_prefill_compiles.inc(traced)
        else:
            self._m_prefill.observe(t_end - t0)
        self.tracer.span(req.req_id, "prefill_chunk", t0, t_end,
                         offset=start, tokens=size,
                         warm=not traced)
        req.prefill_pos += size
        if req.prefill_pos >= L:
            self._complete_prefill(req, first, row)

    def _complete_prefill(self, req: GenerationRequest, first: int,
                          row: np.ndarray):
        slot = req.slot
        req.seq_len = len(req.prompt_ids)
        req.draft_len = req.seq_len        # draft pool mirrored the prompt
        req.state = "running"
        if self.prefix_cache is not None:
            # publish this prompt's full pages for future admissions
            self.prefix_cache.register(req.prompt_ids, req.block_ids)
        self._append_token(req, first)
        if self.slots[slot] is req:         # still running after budget
            self._tokens[slot] = first
            self._seq_lens[slot] = req.seq_len
            self._bt[slot] = row[0]

    # ---- batched decode -------------------------------------------------
    def _grow_pages(self) -> List[int]:
        """Lazy mode: before the fused step runs, every running slot
        must own a real page for the position it writes this step
        (seq_len).  A slot that needs a page neither the pool nor
        prefix-cache eviction can supply is the VICTIM: it is finished
        early with ``truncated=True`` — its pages return to the pool
        (often unblocking the others) and the batch keeps decoding.
        step() never raises for pool exhaustion."""
        truncated = []
        for i, r in enumerate(list(self.slots)):
            if r is None or r.state != "running":
                continue
            need = self.caches[0].blocks_needed(r.seq_len + 1)
            grew = True
            while len(r.block_ids) < need:
                blk = self._try_alloc()
                if blk is None:
                    grew = False
                    break
                self._bt[i, len(r.block_ids)] = blk
                r.block_ids.append(blk)
            if not grew:
                r.truncated = True
                self._m_truncated.inc()
                self._finish(r)
                truncated.append(r.req_id)
        return truncated

    def _decode_batch(self) -> List[int]:
        done = self._grow_pages() if self.lazy_alloc else []
        if not any(r is not None and r.state == "running"
                   for r in self.slots):
            return done
        # ONE fused XLA call at the fixed slot count; masked slots
        # (empty OR still prefilling) ride along — their writes hit the
        # sink page, their token is ignored
        t_decode = time.perf_counter()
        # DecodeStep returns np.asarray(...) — the host fetch inside
        # the call is the device barrier, so this window is honest
        nxt = self.decode_step(self._tokens, self._seq_lens, self._bt,
                               self._samp if self.sampling else None)
        t_end = time.perf_counter()
        if self._decode_warm:
            self._m_decode.observe(t_end - t_decode)
        self._decode_warm = True
        if self.tp is not None:
            self._count_collectives(
                self.decode_step.collective_bytes(self.max_batch_size))
        if self.tracer.enabled:
            for r in self.slots:
                if r is not None and r.state == "running":
                    self.tracer.sample_span(
                        r.req_id, "decode_step", t_decode, t_end,
                        every=self.trace_decode_every)
        for i, r in enumerate(list(self.slots)):
            if r is None or r.state != "running":
                continue
            r.seq_len += 1
            self._seq_lens[i] += 1
            tok = int(nxt[i])
            self._append_token(r, tok)
            if self.slots[i] is r:
                self._tokens[i] = tok
            if r.state == "done":
                done.append(r.req_id)
        return done

    # ---- fused mixed prefill+decode step --------------------------------
    @staticmethod
    def _samp_row(req: GenerationRequest, seed_xor: int = 0) -> np.ndarray:
        """The request's packed sampling knobs: (temperature bits,
        top_k, top_p bits, seed) — fp knobs bitcast into the int32
        lane.  ``seed_xor`` derives the draft engine's independent
        proposal stream from the same request seed."""
        row = np.empty(4, np.int32)
        row[0] = np.float32(req.temperature).view(np.int32)
        row[1] = req.top_k
        row[2] = np.float32(req.top_p).view(np.int32)
        row[3] = (req.seed ^ seed_xor) & 0x7FFFFFFF
        return row

    def _fill_mixed_pack(self, mx, budgets, spans):
        """Fill one MixedStep pack from span tuples
        ``(req, tokens, start, n_draft, seed_xor, masked)``: the span's
        tokens land at global positions ``start..start+m-1`` (kv_len =
        start+m), pages from the request's block table, sampling-knob
        columns when the step compiles them.  ``masked`` spans keep the
        padding descriptor (writes to the sink page, all-sink block
        table) but still occupy their span row, so output/probs rows
        stay slot-aligned across launches.  Returns ``(pack, B)``."""
        total = sum(len(t) for _, t, _, _, _, _ in spans)
        B = next(b for b in budgets if b >= total)
        bs = self.block_size
        W = self.bt_width
        pack, tok_tab, span_tab = mx.new_pack(B)
        tokens, positions, dest_blocks, dest_offsets = tok_tab
        tokens[:] = 0
        positions[:] = 0
        # padding tokens: distinct sink-page slots (garbage on garbage)
        dest_blocks[:] = self._sink
        dest_offsets[:] = self._dest_pad[:B]
        # padding spans pin their offset past the last token so the
        # traced span-of-token search never maps a real token to them
        span_tab[:, :W] = self._sink
        span_tab[:, W] = B          # q_offset
        span_tab[:, W + 1] = 0      # q_len
        span_tab[:, W + 2] = 1      # kv_len
        span_tab[:, W + 3] = 0      # sample_row
        nd_col = W + 4 if mx.spec_k else -1
        sc = W + 4 + (1 if mx.spec_k else 0)
        off = 0
        for si, (r, toks, start, nd, sxor, masked) in enumerate(spans):
            m = len(toks)
            row = span_tab[si]
            row[W] = off
            row[W + 1] = m
            row[W + 3] = off + m - 1
            if masked:
                # keep the slot-aligned row but touch nothing live:
                # block table stays all-sink, writes stay on the sink
                # page, kv_len covers only the span itself
                row[W + 2] = m
                tokens[off:off + m] = toks
                positions[off:off + m] = np.arange(m, dtype=np.int32)
                off += m
                continue
            row[W + 2] = start + m
            row[:len(r.block_ids)] = r.block_ids
            if nd_col >= 0:
                row[nd_col] = nd
            if mx.sampling:
                row[sc:sc + 4] = self._samp_row(r, sxor)
            pos = np.arange(start, start + m, dtype=np.int32)
            tokens[off:off + m] = toks
            positions[off:off + m] = pos
            dest_blocks[off:off + m] = [r.block_ids[p // bs]
                                        for p in pos]
            dest_offsets[off:off + m] = pos % bs
            off += m
        return pack, B

    def _pack_spans(self):
        """Choose this step's ragged span set: every running slot's
        decode token (all must advance), then pending prefill chunks
        round-robin over prefilling slots while the TOP budget has room
        — multiple chunks per step, the round-robin latency killer.
        The chunk half is ``_pick_chunks`` — the ONE chunk-selection
        policy, shared with the speculative round's draft mirror."""
        spans = []                    # (req, kind, size, start)
        total = 0
        for r in self.slots:
            if r is not None and r.state == "running":
                spans.append((r, "decode", 1, r.seq_len))
                total += 1
        for r, size, start in self._pick_chunks(
                self.token_budgets[-1] - total):
            spans.append((r, "prefill", size, start))
            total += size
        return spans, total

    def _run_mixed_step(self) -> List[int]:
        """Pack the admission mix into ONE fused MixedStep launch: build
        the per-token and per-span tables on the host (control flow),
        pad to the smallest token budget, dispatch, then apply the same
        bookkeeping the split decode/prefill paths used."""
        if self.draft_step is not None:
            return self._run_spec_round()
        done = self._grow_pages() if self.lazy_alloc else []
        spans, total = self._pack_spans()
        if not spans:
            return done
        fill = [(r,
                 np.asarray([self._tokens[r.slot]], np.int32)
                 if kind == "decode"
                 else r.prompt_ids[start:start + size].astype(np.int32),
                 start, 0, 0, False)
                for r, kind, size, start in spans]
        pack, B = self._fill_mixed_pack(self.mixed, self.token_budgets,
                                        fill)

        t0 = time.perf_counter()
        pre = self.mixed.total_compiles
        nxt = self.mixed.call_packed(pack, B)
        traced = self.mixed.total_compiles - pre
        dt = time.perf_counter() - t0
        if self.tp is not None:
            self._count_collectives(self.mixed.collective_bytes(B))
        n_dec = sum(1 for _, kind, _, _ in spans if kind == "decode")
        n_pre = total - n_dec
        if n_dec:
            self._m_mixed_tok_decode.inc(n_dec)
        if n_pre:
            self._m_mixed_tok_prefill.inc(n_pre)
        if self._moe_layers:
            # dropless dispatch: every real token lands on exactly
            # top_k experts per MoE layer, none are dropped
            self._m_moe_routed.inc(total * self._moe_topk
                                   * self._moe_layers)
        if traced:
            # first trace of this budget: count it, keep the compile
            # warmup out of every latency histogram
            self._m_mixed_compiles.inc(traced)
        else:
            # the fused step IS both the decode round and the prefill
            # round — classify its (warm) duration into whichever
            # histograms the pack actually advanced
            if n_dec:
                self._m_decode.observe(dt)
            if n_pre:
                self._m_prefill.observe(dt)
        if self.tracer.enabled:
            # every span in the pack shares the one launch window
            t1 = t0 + dt
            for r, kind, size, start in spans:
                if kind == "decode":
                    self.tracer.sample_span(
                        r.req_id, "decode_step", t0, t1,
                        every=self.trace_decode_every)
                else:
                    self.tracer.span(r.req_id, "prefill_chunk", t0, t1,
                                     offset=start, tokens=size,
                                     warm=not traced)

        for si, (r, kind, size, start) in enumerate(spans):
            tok = int(nxt[si])
            if kind == "decode":
                i = r.slot
                r.seq_len += 1
                self._seq_lens[i] += 1
                self._append_token(r, tok)
                if self.slots[i] is r:
                    self._tokens[i] = tok
                if r.state == "done":
                    done.append(r.req_id)
            else:
                r.prefill_pos += size
                if r.prefill_pos >= len(r.prompt_ids):
                    # final chunk: tok is the on-device-sampled first
                    # token (earlier chunks' samples are discarded)
                    self._complete_prefill(r, tok, self._row_for(r))
                    if r.state == "done":
                        done.append(r.req_id)
        return done

    # ---- speculative decoding (draft_model=) ----------------------------
    def _spec_k_eff(self, req: GenerationRequest) -> int:
        """Draft depth for this request this round: never propose past
        the generation budget (a round emits at most k_eff+1 tokens)."""
        remaining = req.max_new_tokens - len(req.output_ids)
        return max(0, min(self.spec_k, remaining - 1))

    def _grow_spec_pages(self, keff: Dict[int, int]):
        """Lazy mode: pages for the k_eff draft positions past the
        mandatory seq_len write are OPPORTUNISTIC — when the pool can't
        cover a slot's full draft depth, the depth shrinks instead of
        truncating the request (the mandatory page was grown by
        ``_grow_pages`` already)."""
        c = self.caches[0]
        for r in self.slots:
            if r is None or r.state != "running":
                continue
            k = keff.get(r.slot, 0)
            while k > 0:
                need = c.blocks_needed(r.seq_len + 1 + k)
                ok = True
                while len(r.block_ids) < need:
                    blk = self._try_alloc()
                    if blk is None:
                        ok = False
                        break
                    self._bt[r.slot, len(r.block_ids)] = blk
                    r.block_ids.append(blk)
                if ok:
                    break
                k -= 1
            keff[r.slot] = k

    def _pick_chunks(self, room: int):
        """Pending prefill chunks for this round, round-robin over
        prefilling slots while ``room`` holds (the same policy as
        ``_pack_spans``; shared by the draft mirror and the verify
        pack, which must see identical chunk work)."""
        spans = []
        n = self.max_batch_size
        advanced_first = None
        for k in range(n):
            i = (self._chunk_rr + k) % n
            r = self.slots[i]
            if r is None or r.state != "prefilling":
                continue
            if room <= 0:
                break
            size = min(self.chunk_size,
                       len(r.prompt_ids) - r.prefill_pos, room)
            if size <= 0:
                continue
            spans.append((r, size, r.prefill_pos))
            room -= size
            if advanced_first is None:
                advanced_first = i
        if advanced_first is not None:
            self._chunk_rr = (advanced_first + 1) % n
        return spans

    def _run_draft_round(self, run_spans, chunk_spans, drafts):
        """The round's ``spec_k`` fused draft-model launches.  Launch 0
        packs every running slot's catch-up span (the 1-2 accepted
        tokens the draft pool hasn't seen, ending at the current token)
        TOGETHER with the round's prefill-chunk mirrors, so the draft
        pool prefills the same prompts in the same rounds; launches
        1..k-1 feed each freshly proposed token back.  A slot whose
        draft depth is capped below the launch index rides along
        MASKED (sink writes), keeping output rows slot-aligned.  Fills
        ``drafts[slot] = [d1..]``; returns the per-launch filtered
        proposal distributions (device-resident) for the verifier's
        rejection-resampling."""
        from ..ops.sampling import DRAFT_SEED_XOR
        q_list = []
        for i in range(self.spec_k):
            spans = []
            for r, k_eff in run_spans:
                # depth-capped slots stop feeding live pages (their
                # later proposals are never verified) — and a masked
                # span only needs ONE placeholder token to keep the
                # output/probs rows slot-aligned
                masked = i >= k_eff
                if i == 0 and not masked:
                    cu = r.seq_len + 1 - r.draft_len
                    toks = np.asarray(r.output_ids[-cu:], np.int32)
                    start = r.draft_len
                elif masked:
                    toks = np.asarray([r.output_ids[-1]], np.int32)
                    start = r.seq_len + i
                else:
                    toks = np.asarray([drafts[r.slot][i - 1]], np.int32)
                    start = r.seq_len + i
                spans.append((r, toks, start, 0, DRAFT_SEED_XOR,
                              masked))
            if i == 0:
                for r, size, start in chunk_spans:
                    spans.append(
                        (r, r.prompt_ids[start:start + size]
                         .astype(np.int32), start, 0, DRAFT_SEED_XOR,
                         False))
            if not spans:
                break
            t0 = time.perf_counter()
            pre = self.draft_step.total_compiles
            pack, B = self._fill_mixed_pack(self.draft_step,
                                            self.draft_budgets, spans)
            out = self.draft_step.call_packed(pack, B)
            if self.sampling:
                toks_np, probs = out
                q_list.append(probs)
            else:
                toks_np = out
            if self.draft_step.total_compiles == pre:
                self._m_draft_step.observe(time.perf_counter() - t0)
            for si, (r, _k) in enumerate(run_spans):
                drafts[r.slot].append(int(toks_np[si]))
            if not run_spans:
                break               # chunk mirror only, nothing to feed
        return q_list

    def _run_spec_round(self) -> List[int]:
        """One speculative engine round: k fused draft launches propose
        per-slot token chains, ONE fused MixedStep launch verifies all
        slots' k+1 positions (and advances prefill chunks riding the
        same pack), and the host applies the accepted prefix + the
        corrected/bonus token.  Greedy output is byte-identical to the
        non-speculative engine; sampled output is distribution-exact
        (rejection-resampling on device)."""
        done = self._grow_pages() if self.lazy_alloc else []
        keff: Dict[int, int] = {}
        for r in self.slots:
            if r is not None and r.state == "running":
                keff[r.slot] = self._spec_k_eff(r)
        if self.lazy_alloc:
            self._grow_spec_pages(keff)
        run_spans = [(r, keff[r.slot]) for r in self.slots
                     if r is not None and r.state == "running"]
        total_v = sum(k + 1 for _, k in run_spans)
        # chunk room must fit BOTH packs that carry the chunks: the
        # verify pack (k_eff+1 tokens per running slot) and the draft's
        # launch 0 (at most 2 catch-up tokens per running slot)
        chunk_spans = self._pick_chunks(
            min(self.token_budgets[-1] - total_v,
                self.draft_budgets[-1] - 2 * len(run_spans)))
        if not run_spans and not chunk_spans:
            return done

        drafts: Dict[int, List[int]] = {r.slot: [] for r, _ in run_spans}
        q_list = self._run_draft_round(run_spans, chunk_spans, drafts)

        v_spans = []
        for r, k_eff in run_spans:
            toks = np.empty(k_eff + 1, np.int32)
            toks[0] = self._tokens[r.slot]
            if k_eff:
                toks[1:] = drafts[r.slot][:k_eff]
            v_spans.append((r, toks, r.seq_len, k_eff, 0, False))
        for r, size, start in chunk_spans:
            v_spans.append((r, r.prompt_ids[start:start + size]
                            .astype(np.int32), start, 0, 0, False))
        pack, B = self._fill_mixed_pack(self.mixed, self.token_budgets,
                                        v_spans)
        q_probs = None
        if self.sampling:
            while len(q_list) < self.spec_k:
                q_list.append(self._zero_q)
            q_probs = tuple(q_list)

        t0 = time.perf_counter()
        pre = self.mixed.total_compiles
        nxt, n_acc = self.mixed.call_packed(pack, B, q_probs=q_probs)
        traced = self.mixed.total_compiles - pre
        dt = time.perf_counter() - t0
        n_pre = sum(size for _, size, _ in chunk_spans)
        if traced:
            self._m_mixed_compiles.inc(traced)
        else:
            if run_spans:
                self._m_decode.observe(dt)
            if n_pre:
                self._m_prefill.observe(dt)
        if n_pre:
            self._m_mixed_tok_prefill.inc(n_pre)
        if self.tracer.enabled:
            # one verify launch advanced every slot (and the chunk
            # mirrors): sampled decode spans + chunk spans share its
            # window, exactly like the non-speculative mixed step
            t1 = t0 + dt
            for r, _k in run_spans:
                self.tracer.sample_span(
                    r.req_id, "decode_step", t0, t1,
                    every=self.trace_decode_every, speculative=True)
            for r, size, start in chunk_spans:
                self.tracer.span(r.req_id, "prefill_chunk", t0, t1,
                                 offset=start, tokens=size,
                                 warm=not traced)

        emitted = 0
        for si, (r, toks, start, nd, _x, _m) in enumerate(v_spans):
            if r.state == "prefilling":
                r.prefill_pos += len(toks)
                if r.prefill_pos >= len(r.prompt_ids):
                    self._complete_prefill(r, int(nxt[si]),
                                           self._row_for(r))
                    if r.state == "done":
                        done.append(r.req_id)
                continue
            na = int(n_acc[si])
            k_eff = nd
            self._m_spec_proposed.inc(k_eff)
            self._m_spec_accepted.inc(na)
            # draft-pool correctness mark BEFORE advancing seq_len:
            # the slot's live launches fed cur@s and d1..d_{k_eff-1},
            # and the correct prefix ends at the last ACCEPTED fed
            # position — next round's catch-up span starts there
            if k_eff >= 1:
                r.draft_len = r.seq_len + 1 + min(na, k_eff - 1)
            out_toks = drafts[r.slot][:na] + [int(nxt[si])]
            for t in out_toks:
                r.seq_len += 1
                self._seq_lens[r.slot] += 1
                emitted += 1
                self._append_token(r, t)
                if r.state == "done":
                    done.append(r.req_id)
                    break
            if self.slots[r.slot] is r:
                self._tokens[r.slot] = r.output_ids[-1]
                if self.lazy_alloc:
                    # roll back pages grown for rejected draft
                    # positions through the refcounted release path
                    c = self.caches[0]
                    keep = len(c.trim_blocks(r.block_ids,
                                             r.seq_len + 1))
                    del r.block_ids[keep:]
                    self._bt[r.slot, keep:] = self._sink
        if emitted:
            self._m_mixed_tok_decode.inc(emitted)
        return done

    # ---- bookkeeping ----------------------------------------------------
    def _count_collectives(self, by_op: Dict[str, int]):
        """Publish one sharded dispatch's per-chip collective payload
        (host-side accounting — the byte counts are static per compiled
        shape, so nothing is fetched from the device).  When the logits
        all-gather is quantized, its (already-int8-sized) payload is
        additionally counted under the quantized-collective family."""
        if by_op.get("psum"):
            self._m_tp_psum.inc(by_op["psum"])
        if by_op.get("all_gather"):
            self._m_tp_all_gather.inc(by_op["all_gather"])
            if self.quant_collectives:
                self._m_quant_all_gather.inc(by_op["all_gather"])
        if by_op.get("cp_merge"):
            self._m_cp_all_gather.inc(by_op["cp_merge"])
        if by_op.get("ep_all_to_all"):
            self._m_ep_all_to_all.inc(by_op["ep_all_to_all"])
        if by_op.get("ep_all_gather"):
            self._m_ep_all_gather.inc(by_op["ep_all_gather"])
        if self._fsdp_gather_bytes:
            self._m_fsdp_gather.inc(self._fsdp_gather_bytes)

    def record_token_mismatches(self, n: int):
        """Feed the quant token-mismatch counter (callers: the paired
        fp32-vs-quant bench/test harnesses that actually know the
        reference tokens)."""
        if n:
            self._m_quant_mismatch.inc(int(n))

    def _append_token(self, req: GenerationRequest, token: int):
        req.output_ids.append(token)
        self.counters["tokens_generated"] += 1
        if len(req.output_ids) == 1:
            req.t_first_token = time.perf_counter()
            if req.t_submit:
                self._m_ttft.observe(req.t_first_token - req.t_submit)
            self.tracer.event(
                req.req_id, "first_token", ts=req.t_first_token,
                ttft=(req.t_first_token - req.t_submit
                      if req.t_submit else 0.0))
        hit_eos = (req.eos_token_id is not None
                   and token == req.eos_token_id)
        if len(req.output_ids) >= req.max_new_tokens or hit_eos:
            self._finish(req)

    def _release_slot(self, req: GenerationRequest):
        """Mask the request's slot back to the sink page and release
        its pages through the ONE refcounted path.  Shared by
        ``_finish`` and ``preempt_request`` — every per-slot state
        field (tokens, seq_lens, block table, sampling knobs) must be
        cleared HERE and nowhere else, so the two release sites cannot
        drift as new fields are added."""
        if req.slot >= 0:
            s = req.slot
            self.slots[s] = None
            self._tokens[s] = 0
            self._seq_lens[s] = 0
            self._bt[s, :] = self._sink
            self._samp[s, :] = 0
        # the SINGLE release path: refcounted — pages shared with the
        # prefix table or another live request survive this drop
        self.caches[0].free_sequence(req.block_ids)
        req.block_ids = []

    def _finish(self, req: GenerationRequest):
        req.state = "done"
        # surface admission-time completions in this step()'s return
        # (the decode/mixed loops build their own lists; step() dedupes)
        if getattr(self, "_finished_this_step", None) is not None:
            self._finished_this_step.append(req.req_id)
        n_tok = len(req.output_ids)
        self._m_requests.labels(
            outcome="truncated" if req.truncated else "completed").inc()
        self._m_tokens.inc(n_tok)
        req.t_done = time.perf_counter()
        if n_tok > 1 and req.t_first_token:
            self._m_tpot.observe(
                (req.t_done - req.t_first_token) / (n_tok - 1))
        self.tracer.event(
            req.req_id, "finish", ts=req.t_done, tokens=n_tok,
            outcome="truncated" if req.truncated else "completed")
        self._release_slot(req)
        self.finished[req.req_id] = req
