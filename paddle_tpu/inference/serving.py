"""Continuous batching over the paged KV cache.

Parity: the reference serving stack's batched multi-request execution —
block_multihead_attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)
driven by a request scheduler around AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:210 ZeroCopyRun).

TPU-native design: the scheduler keeps a fixed number of decode SLOTS and
one engine step is ONE jitted XLA module (jit/serving_step.DecodeStep)
at that fixed slot count — all layers, the paged cache append, paged
attention, the LM head and greedy sampling fused, with the per-layer KV
pools donated so the append is an in-place HBM write.  Inactive slots
are masked (token 0, seq_len 0, block table aimed at the cache's sink
page), never dropped, so admission/eviction churn never changes a traced
shape and the decode step compiles exactly once for the engine's
lifetime.  Requests are admitted into free slots per step: the prompt is
prefilled through the model's dense path and its per-layer K/V scattered
into cache pages in one fused call per request; finished slots release
their pages immediately, making room for waiting requests mid-flight.
Admission/eviction is host control flow; all math is jitted device
compute, and the only per-step host traffic is the [slots] int32
next-token fetch.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..ops.paged_attention import PagedKVCache


@dataclass
class GenerationRequest:
    """One in-flight generation (parity: the request objects the
    reference serving runtime schedules)."""
    req_id: int
    prompt_ids: np.ndarray                 # [L] int
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    output_ids: List[int] = field(default_factory=list)
    state: str = "waiting"                 # waiting -> running -> done
    # True when the engine ran out of KV pages mid-decode and finished
    # this request early instead of wedging the whole batch
    truncated: bool = False

    # slot bookkeeping (set while running)
    slot: int = -1
    seq_len: int = 0
    block_ids: List[int] = field(default_factory=list)
    # telemetry marks (perf_counter): admission -> first token = TTFT,
    # first token -> done over n-1 tokens = TPOT
    t_submit: float = 0.0
    t_first_token: float = 0.0


class ContinuousBatchingEngine:
    """Slot scheduler + single-compile batched paged decode for
    LlamaForCausalLM.

    add_request() may be called at any time (including between steps
    while other requests are mid-decode); step() advances every running
    request by one token.  Greedy decoding — interleaved execution is
    bit-identical to running each request alone (the test contract).

    ``max_seq_len`` bounds prompt + generation per request and fixes the
    block-table width (the compiled decode step's shape); it defaults to
    the pool's fair share per slot, num_blocks * block_size //
    max_batch_size.
    """

    def __init__(self, model, max_batch_size: int = 8,
                 num_blocks: int = 256, block_size: int = 16,
                 max_seq_len: Optional[int] = None,
                 use_pallas: Optional[bool] = None,
                 lazy_alloc: bool = False):
        from ..jit.serving_step import DecodeStep
        self.model = model
        # lazy_alloc: pages are allocated as a sequence actually grows
        # instead of reserving the full prompt+budget footprint at
        # admission — higher occupancy for the same pool, at the cost
        # that the pool CAN run dry mid-decode.  When it does, the
        # victim request is finished early with ``truncated=True``
        # (robustness contract: step() never raises out of a full
        # batch; the other slots keep decoding).
        self.lazy_alloc = bool(lazy_alloc)
        cfg = model.config
        self.cfg = cfg
        self.max_batch_size = max_batch_size
        self.block_size = block_size
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.caches = [
            PagedKVCache(num_blocks, block_size,
                         cfg.num_key_value_heads, self.head_dim, dtype,
                         sink_block=True)
            for _ in range(cfg.num_hidden_layers)]
        if max_seq_len is None:
            max_seq_len = max(block_size,
                              num_blocks * block_size // max_batch_size)
        self.max_seq_len = max_seq_len
        self.bt_width = -(-max_seq_len // block_size)
        self._sink = self.caches[0].sink
        self.slots: List[Optional[GenerationRequest]] = \
            [None] * max_batch_size
        self.waiting: List[GenerationRequest] = []
        self.finished: Dict[int, GenerationRequest] = {}
        self._next_id = 0
        # slot-padded device-step inputs (fixed shapes forever): masked
        # slots hold token 0 / seq_len 0 / an all-sink block-table row
        self._tokens = np.zeros((max_batch_size,), np.int32)
        self._seq_lens = np.zeros((max_batch_size,), np.int32)
        self._bt = np.full((max_batch_size, self.bt_width), self._sink,
                           np.int32)
        self.decode_step = DecodeStep(model, self.caches,
                                      use_pallas=use_pallas)

        from ..observability import default_registry
        r = default_registry()
        self._m_queue = r.gauge(
            "serving_queue_depth", "requests waiting for a free slot")
        self._m_occupancy = r.gauge(
            "serving_slot_occupancy_ratio",
            "running slots / max_batch_size")
        self._m_kv_util = r.gauge(
            "serving_kv_page_utilization_ratio",
            "allocated KV pages / pool size")
        self._m_prefill = r.histogram(
            "serving_prefill_duration_seconds",
            "prompt prefill (dense forward + fused cache scatter)")
        self._m_decode = r.histogram(
            "serving_decode_step_duration_seconds",
            "one fused batched decode step (all slots)")
        self._m_ttft = r.histogram(
            "serving_ttft_seconds", "admission wait + prefill to first "
            "token (time-to-first-token)")
        self._m_tpot = r.histogram(
            "serving_tpot_seconds",
            "mean per-token decode latency after the first token",
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0))
        self._m_requests = r.counter(
            "serving_requests_total", "finished generation requests",
            labels=("outcome",))
        self._m_tokens = r.counter(
            "serving_tokens_total", "tokens generated")
        self._m_truncated = r.counter(
            "serving_truncated_victims_total",
            "requests finished early because the KV pool ran dry "
            "(lazy_alloc victim contract)")
        # compile warmup never lands in a latency histogram: the first
        # decode call traces the fused step; the dense prefill path
        # re-traces PER PROMPT LENGTH, so warmth is per-length
        self._prefill_warm_lens = set()
        self._decode_warm = False

    # ---- public API ----------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=16,
                    eos_token_id=None) -> int:
        req = GenerationRequest(
            req_id=self._next_id,
            prompt_ids=np.asarray(prompt_ids, np.int64).reshape(-1),
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
        need = self.caches[0].blocks_needed(
            len(req.prompt_ids) + max_new_tokens)
        if need > self.bt_width:
            raise ValueError(
                "request needs %d pages but the engine's block-table "
                "width is %d (max_seq_len=%d); raise max_seq_len"
                % (need, self.bt_width, self.max_seq_len))
        min_need = need if not self.lazy_alloc else \
            self.caches[0].blocks_needed(len(req.prompt_ids) + 1)
        if min_need > self.caches[0].num_blocks:
            # would never admit: _admit waits for pages that can't exist
            # (lazy mode only needs the prompt to fit — the tail may be
            # truncated if the pool runs dry)
            raise ValueError(
                "request needs %d pages but the pool only has %d; "
                "raise num_blocks" % (min_need, self.caches[0].num_blocks))
        self._next_id += 1
        req.t_submit = time.perf_counter()
        self.waiting.append(req)
        self._m_queue.set(len(self.waiting))
        return req.req_id

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None
                                         for s in self.slots)

    def step(self) -> List[int]:
        """Admit waiting requests, decode one token for every running
        slot.  Returns req_ids finished this step."""
        self._admit()
        done = self._decode_batch()
        self._m_queue.set(len(self.waiting))
        self._m_occupancy.set(
            sum(s is not None for s in self.slots)
            / max(1, self.max_batch_size))
        cache = self.caches[0]
        self._m_kv_util.set(
            1.0 - len(cache._free) / max(1, cache.num_blocks))
        return done

    def run_to_completion(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        return {rid: r.output_ids for rid, r in self.finished.items()}

    def result(self, req_id: int) -> List[int]:
        return self.finished[req_id].output_ids

    # ---- admission (prefill) -------------------------------------------
    def _admit(self):
        for i in range(self.max_batch_size):
            if not self.waiting or self.slots[i] is not None:
                continue
            req = self.waiting[0]
            L = len(req.prompt_ids)
            need = (self.caches[0].blocks_needed(L + 1) if self.lazy_alloc
                    else self.caches[0].blocks_needed(
                        L + req.max_new_tokens))
            if len(self.caches[0]._free) < need:
                break                       # no room yet: keep waiting
            self.waiting.pop(0)
            self._prefill(req, i)

    def _prefill(self, req: GenerationRequest, slot: int):
        """Run the prompt through the model's dense path once, scatter
        the per-layer K/V into cache pages with ONE fused call, sample
        the first token."""
        import paddle_tpu as paddle
        from ..autograd.tape import no_grad
        from ..jit.serving_step import prefill_scatter
        t_prefill = time.perf_counter()
        L = len(req.prompt_ids)
        ids = paddle.to_tensor(req.prompt_ids[None, :].astype(np.int64))
        with no_grad():
            logits, kv = self.model.forward(
                ids, caches=[(None, None)] * self.cfg.num_hidden_layers)
        # allocate pages covering prompt + generation budget up front
        # (lazy mode: prompt + the first decode position only; the rest
        # are grown page-by-page in _decode_batch).  Pools share the
        # free-list of cache 0 so one table serves every layer.
        n_blocks = (self.caches[0].blocks_needed(L + 1) if self.lazy_alloc
                    else self.caches[0].blocks_needed(
                        L + req.max_new_tokens))
        req.block_ids = [self.caches[0].allocate_block()
                         for _ in range(n_blocks)]
        row = np.full((1, self.bt_width), self._sink, np.int32)
        row[0, :n_blocks] = req.block_ids
        # k/v [1, L, Hkv, D] pre-GQA-repeat — one donated scatter over
        # ALL layers (not a Python loop of per-layer dispatches)
        prefill_scatter(self.caches, kv, row)
        req.slot = slot
        req.seq_len = L
        req.state = "running"
        self.slots[slot] = req
        last = np.asarray(logits[:, -1, :]._value, np.float32)
        first = int(last[0].argmax())
        if L in self._prefill_warm_lens:
            self._m_prefill.observe(time.perf_counter() - t_prefill)
        self._prefill_warm_lens.add(L)
        self._append_token(req, first)
        if self.slots[slot] is req:         # still running after budget
            self._tokens[slot] = first
            self._seq_lens[slot] = L
            self._bt[slot] = row[0]

    # ---- batched decode -------------------------------------------------
    def _grow_pages(self) -> List[int]:
        """Lazy mode: before the fused step runs, every running slot
        must own a real page for the position it writes this step
        (seq_len).  A slot that needs a page the pool cannot supply is
        the VICTIM: it is finished early with ``truncated=True`` — its
        pages return to the pool (often unblocking the others) and the
        batch keeps decoding.  step() never raises for pool exhaustion."""
        truncated = []
        for i, r in enumerate(list(self.slots)):
            if r is None:
                continue
            need = self.caches[0].blocks_needed(r.seq_len + 1)
            grew = True
            while len(r.block_ids) < need:
                if not self.caches[0]._free:
                    grew = False
                    break
                blk = self.caches[0].allocate_block()
                self._bt[i, len(r.block_ids)] = blk
                r.block_ids.append(blk)
            if not grew:
                r.truncated = True
                self._m_truncated.inc()
                self._finish(r)
                truncated.append(r.req_id)
        return truncated

    def _decode_batch(self) -> List[int]:
        done = self._grow_pages() if self.lazy_alloc else []
        if all(r is None for r in self.slots):
            return done
        # ONE fused XLA call at the fixed slot count; masked slots ride
        # along (their writes hit the sink page, their token is ignored)
        t_decode = time.perf_counter()
        # DecodeStep returns np.asarray(...) — the host fetch inside
        # the call is the device barrier, so this window is honest
        nxt = self.decode_step(self._tokens, self._seq_lens, self._bt)
        if self._decode_warm:
            self._m_decode.observe(time.perf_counter() - t_decode)
        self._decode_warm = True
        for i, r in enumerate(list(self.slots)):
            if r is None:
                continue
            r.seq_len += 1
            self._seq_lens[i] += 1
            tok = int(nxt[i])
            self._append_token(r, tok)
            if self.slots[i] is r:
                self._tokens[i] = tok
            if r.state == "done":
                done.append(r.req_id)
        return done

    # ---- bookkeeping ----------------------------------------------------
    def _append_token(self, req: GenerationRequest, token: int):
        req.output_ids.append(token)
        if len(req.output_ids) == 1:
            req.t_first_token = time.perf_counter()
            if req.t_submit:
                self._m_ttft.observe(req.t_first_token - req.t_submit)
        hit_eos = (req.eos_token_id is not None
                   and token == req.eos_token_id)
        if len(req.output_ids) >= req.max_new_tokens or hit_eos:
            self._finish(req)

    def _finish(self, req: GenerationRequest):
        req.state = "done"
        n_tok = len(req.output_ids)
        self._m_requests.labels(
            outcome="truncated" if req.truncated else "completed").inc()
        self._m_tokens.inc(n_tok)
        if n_tok > 1 and req.t_first_token:
            self._m_tpot.observe(
                (time.perf_counter() - req.t_first_token) / (n_tok - 1))
        if req.slot >= 0:
            s = req.slot
            self.slots[s] = None
            self._tokens[s] = 0
            self._seq_lens[s] = 0
            self._bt[s, :] = self._sink
        self.caches[0].free_sequence(req.block_ids)
        req.block_ids = []
        self.finished[req.req_id] = req
