"""Continuous batching over the paged KV cache.

Parity: the reference serving stack's batched multi-request execution —
block_multihead_attention
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)
driven by a request scheduler around AnalysisPredictor
(paddle/fluid/inference/api/analysis_predictor.h:210).

TPU-native design: the scheduler keeps a fixed number of decode SLOTS
(static shapes — one compiled decode step reused forever); requests are
admitted into free slots per step (prompt prefilled through the model's
dense path, K/V scattered into cache pages), every active slot decodes
one token per engine step via the paged-attention kernel, and finished
slots release their pages immediately, making room for waiting requests
mid-flight.  Admission/eviction is host control flow; all math is jitted
device compute.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.paged_attention import PagedKVCache, paged_attention


@dataclass
class GenerationRequest:
    """One in-flight generation (parity: the request objects the
    reference serving runtime schedules)."""
    req_id: int
    prompt_ids: np.ndarray                 # [L] int
    max_new_tokens: int = 16
    eos_token_id: Optional[int] = None
    output_ids: List[int] = field(default_factory=list)
    state: str = "waiting"                 # waiting -> running -> done

    # slot bookkeeping (set while running)
    slot: int = -1
    seq_len: int = 0
    block_ids: List[int] = field(default_factory=list)


class ContinuousBatchingEngine:
    """Slot scheduler + batched paged decode for LlamaForCausalLM.

    add_request() may be called at any time (including between steps
    while other requests are mid-decode); step() advances every running
    request by one token.  Greedy decoding — interleaved execution is
    bit-identical to running each request alone (the test contract)."""

    def __init__(self, model, max_batch_size: int = 8,
                 num_blocks: int = 256, block_size: int = 16):
        self.model = model
        cfg = model.config
        self.cfg = cfg
        self.max_batch_size = max_batch_size
        self.block_size = block_size
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
        self.caches = [
            PagedKVCache(num_blocks, block_size,
                         cfg.num_key_value_heads, self.head_dim, dtype)
            for _ in range(cfg.num_hidden_layers)]
        self.slots: List[Optional[GenerationRequest]] = \
            [None] * max_batch_size
        self.waiting: List[GenerationRequest] = []
        self.finished: Dict[int, GenerationRequest] = {}
        self._next_id = 0

    # ---- public API ----------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=16,
                    eos_token_id=None) -> int:
        req = GenerationRequest(
            req_id=self._next_id,
            prompt_ids=np.asarray(prompt_ids, np.int64).reshape(-1),
            max_new_tokens=max_new_tokens, eos_token_id=eos_token_id)
        self._next_id += 1
        self.waiting.append(req)
        return req.req_id

    def has_work(self) -> bool:
        return bool(self.waiting) or any(s is not None
                                         for s in self.slots)

    def step(self) -> List[int]:
        """Admit waiting requests, decode one token for every running
        slot.  Returns req_ids finished this step."""
        self._admit()
        done = self._decode_batch()
        return done

    def run_to_completion(self) -> Dict[int, List[int]]:
        while self.has_work():
            self.step()
        return {rid: r.output_ids for rid, r in self.finished.items()}

    def result(self, req_id: int) -> List[int]:
        return self.finished[req_id].output_ids

    # ---- admission (prefill) -------------------------------------------
    def _admit(self):
        for i in range(self.max_batch_size):
            if not self.waiting or self.slots[i] is not None:
                continue
            req = self.waiting[0]
            L = len(req.prompt_ids)
            need = self.caches[0].blocks_needed(L + req.max_new_tokens)
            if len(self.caches[0]._free) < need:
                break                       # no room yet: keep waiting
            self.waiting.pop(0)
            self._prefill(req, i)

    def _prefill(self, req: GenerationRequest, slot: int):
        """Run the prompt through the model's dense path once, scatter
        the per-layer K/V into cache pages, sample the first token."""
        import paddle_tpu as paddle
        from ..autograd.tape import no_grad
        L = len(req.prompt_ids)
        ids = paddle.to_tensor(req.prompt_ids[None, :].astype(np.int64))
        with no_grad():
            logits, kv = self.model.forward(
                ids, caches=[(None, None)] * self.cfg.num_hidden_layers)
        # allocate pages covering prompt + generation budget up front
        # (simple fixed reservation; ensure_capacity grows on demand too)
        n_blocks = self.caches[0].blocks_needed(L + req.max_new_tokens)
        req.block_ids = [self.caches[0].allocate_block()
                         for _ in range(n_blocks)]
        bt = np.asarray(req.block_ids, np.int32)[None, :]
        zeros = np.zeros((1,), np.int32)
        for cache, (k, v) in zip(self.caches, kv):
            # k/v [1, L, Hkv, D] pre-GQA-repeat — prefill scatter at 0.
            # Pools share the free-list of cache 0 so one table serves
            # every layer; write through the functional API.
            from ..ops.paged_attention import write_kv_to_cache
            cache.key_cache, cache.value_cache = write_kv_to_cache(
                k, v, cache.key_cache, cache.value_cache, bt, zeros,
                donate=True)
        req.slot = slot
        req.seq_len = L
        req.state = "running"
        self.slots[slot] = req
        last = np.asarray(logits[:, -1, :]._value, np.float32)
        self._append_token(req, int(last[0].argmax()))

    # ---- batched decode -------------------------------------------------
    def _active(self) -> List[GenerationRequest]:
        return [r for r in self.slots if r is not None]

    def _decode_batch(self) -> List[int]:
        import paddle_tpu as paddle
        from ..autograd.tape import no_grad
        from ..incubate.nn.functional import \
            fused_rotary_position_embedding
        reqs = self._active()
        if not reqs:
            return []
        B = len(reqs)
        tokens = np.asarray([r.output_ids[-1] for r in reqs],
                            np.int64)[:, None]
        seq_lens = np.asarray([r.seq_len for r in reqs], np.int32)
        max_blocks = max(len(r.block_ids) for r in reqs)
        bt = np.full((B, max_blocks), -1, np.int32)
        for i, r in enumerate(reqs):
            bt[i, :len(r.block_ids)] = r.block_ids

        llama = self.model.llama
        cfg = self.cfg
        H = cfg.num_attention_heads
        Hkv = cfg.num_key_value_heads
        D = self.head_dim
        with no_grad():
            x = llama.embed_tokens(paddle.to_tensor(tokens))  # [B,1,h]
            pos = paddle.to_tensor(seq_lens[:, None].astype(np.int32))
            for layer, cache in zip(llama.layers, self.caches):
                h = layer.input_layernorm(x)
                attn = layer.self_attn
                q = attn.q_proj(h).reshape([B, 1, H, D])
                k = attn.k_proj(h).reshape([B, 1, Hkv, D])
                v = attn.v_proj(h).reshape([B, 1, Hkv, D])
                q, k, _ = fused_rotary_position_embedding(
                    q, k, position_ids=pos,
                    rotary_emb_base=cfg.rope_theta)
                cache.append(k[:, 0], v[:, 0], bt, seq_lens)
                out = paged_attention(
                    q[:, 0], cache.key_cache, cache.value_cache, bt,
                    seq_lens + 1)                      # incl. new token
                out = out.reshape([B, 1, H * D])
                x = x + attn.o_proj(out)
                h2 = layer.post_attention_layernorm(x)
                x = x + layer.mlp(h2)
            x = llama.norm(x)
            if self.model.lm_head is None:
                from ..ops.linalg import matmul
                logits = matmul(x, llama.embed_tokens.weight,
                                transpose_y=True)
            else:
                logits = self.model.lm_head(x)
        nxt = np.asarray(logits[:, 0, :]._value, np.float32).argmax(-1)

        done = []
        for i, r in enumerate(reqs):
            r.seq_len += 1
            self._append_token(r, int(nxt[i]))
            if r.state == "done":
                done.append(r.req_id)
        return done

    # ---- bookkeeping ----------------------------------------------------
    def _append_token(self, req: GenerationRequest, token: int):
        req.output_ids.append(token)
        hit_eos = (req.eos_token_id is not None
                   and token == req.eos_token_id)
        if len(req.output_ids) >= req.max_new_tokens or hit_eos:
            self._finish(req)

    def _finish(self, req: GenerationRequest):
        req.state = "done"
        if req.slot >= 0:
            self.slots[req.slot] = None
        self.caches[0].free_sequence(req.block_ids)
        req.block_ids = []
        self.finished[req.req_id] = req
