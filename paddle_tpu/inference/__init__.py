"""Inference deployment API.

Parity: paddle_infer (reference — paddle/fluid/inference/api/
analysis_predictor.h:100,210 AnalysisPredictor/ZeroCopyRun,
paddle_inference_api.h Config/Tensor handles).

TPU-native: the deployed artifact is the StableHLO program written by
``jit.save`` (the PIR/ProgramDesc analog); "analysis passes" are XLA's
job at AOT-compile time, so ``create_predictor`` loads the exported
module, compiles it once per input signature, and ``run`` is a single
device execution with zero-copy numpy in/out.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PredictorPool"]


class Config:
    """Parity: paddle_infer.Config."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        # paddle passes either a dir or (model_file, params_file); here a
        # single prefix identifies path.pdexec/.pdparams/.json
        self._prefix = None
        if model_path is not None:
            self._prefix = (model_path[:-7]
                            if model_path.endswith(".pdexec") else model_path)
        self._device = "tpu"
        self._device_id = 0
        self._enable_memory_optim = True
        self._cpu_math_threads = 1
        self._ir_optim = True

    def set_model(self, model_path, params_path=None):
        self._prefix = (model_path[:-7]
                        if model_path.endswith(".pdexec") else model_path)

    def model_path(self):
        return self._prefix

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accepted for API parity; device selection is JAX's
        self._device = "gpu"
        self._device_id = device_id

    def enable_tpu(self, device_id=0):
        self._device = "tpu"
        self._device_id = device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "gpu"

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        self._ir_optim = flag

    def summary(self):
        return json.dumps({"model": self._prefix, "device": self._device})


class Tensor:
    """Zero-copy handle (parity: paddle_infer.Tensor)."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self._name = name
        self._pred = predictor
        self._is_input = is_input

    def name(self):
        return self._name

    # -- input side --
    def copy_from_cpu(self, data: np.ndarray):
        assert self._is_input, "copy_from_cpu on an output handle"
        self._pred._feed[self._name] = np.asarray(data)

    def reshape(self, shape):
        pass   # shapes follow the fed array; kept for API parity

    # -- output side --
    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input, "copy_to_cpu on an input handle"
        return self._pred._fetch[self._name]

    def shape(self):
        if self._is_input:
            arr = self._pred._feed.get(self._name)
            return list(arr.shape) if arr is not None else None
        return list(self._pred._fetch[self._name].shape)


class Predictor:
    """Parity: paddle_infer.Predictor over a jit.save'd StableHLO module."""

    def __init__(self, config: Config):
        if config.model_path() is None:
            raise ValueError("Config.set_model(path_prefix) is required")
        prefix = config.model_path()
        meta_path = prefix + ".json"
        if not os.path.exists(meta_path):
            raise FileNotFoundError(
                "no exported model at %r (expected %s)" % (prefix, meta_path))
        with open(meta_path) as f:
            self._meta = json.load(f)
        from ..jit.save_load import load as jit_load
        self._layer = jit_load(prefix)
        self._input_names = list(
            self._meta.get("input_names")
            or [f"x{i}" for i in range(len(self._meta["input_shapes"]))])
        self._feed: Dict[str, np.ndarray] = {}
        self._fetch: Dict[str, np.ndarray] = {}
        self._output_names: List[str] = []

    # -- reference API --
    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        if name not in self._input_names:
            raise KeyError(name)
        return Tensor(name, self, is_input=True)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """ZeroCopyRun: execute on the fed inputs (or `inputs` list)."""
        from ..core.tensor import Tensor as PTensor
        if inputs is not None:
            for n, a in zip(self._input_names, inputs):
                self._feed[n] = np.asarray(a)
        missing = [n for n in self._input_names if n not in self._feed]
        if missing:
            raise RuntimeError("inputs not fed: %s" % missing)
        args = [PTensor(self._feed[n]) for n in self._input_names]
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._output_names = [f"out{i}" for i in range(len(outs))]
        self._fetch = {n: np.asarray(o._value)
                       for n, o in zip(self._output_names, outs)}
        if inputs is not None:
            return [self._fetch[n] for n in self._output_names]
        return True

    def get_output_names(self) -> List[str]:
        if not self._output_names:
            # run not called yet: outputs unknown until execution; probe
            # with zeros is unsafe, report the standard single slot
            return ["out0"]
        return list(self._output_names)

    def get_output_handle(self, name: str) -> Tensor:
        return Tensor(name, self, is_input=False)

    def clear_intermediate_tensor(self):
        self._feed.clear()
        self._fetch.clear()

    def try_shrink_memory(self):
        pass


class PredictorPool:
    """Parity: paddle_infer.PredictorPool (N predictors over one model)."""

    def __init__(self, config: Config, size: int = 1):
        self._preds = [Predictor(config) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


from .serving import (ContinuousBatchingEngine,      # noqa: E402,F401
                      GenerationRequest)
from .router import (ServingRouter, EngineHandle,    # noqa: E402,F401
                     RouterRequest, RouterQueueFull)
from .fleet import (RemoteEngineClient, EngineServer,  # noqa: E402,F401
                    EngineProcess, EngineRPCError, RetryPolicy)

__all__ += ["ContinuousBatchingEngine", "GenerationRequest",
            "ServingRouter", "EngineHandle", "RouterRequest",
            "RouterQueueFull", "RemoteEngineClient", "EngineServer",
            "EngineProcess", "EngineRPCError", "RetryPolicy"]


# ---------------------------------------------------------------------------
# enums + version/introspection tail (parity: paddle/inference/__init__.py)
# ---------------------------------------------------------------------------
import enum as _enum


class DataType(_enum.Enum):
    """Parity: paddle_infer.DataType."""
    FLOAT32 = 0
    FLOAT16 = 1
    INT64 = 2
    INT32 = 3
    UINT8 = 4
    INT8 = 5
    BOOL = 6
    BFLOAT16 = 7
    FLOAT64 = 8


class PlaceType(_enum.Enum):
    """Parity: paddle_infer.PlaceType (the accelerator slot is the TPU
    here)."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3
    TPU = 4


class PrecisionType(_enum.Enum):
    """Parity: paddle_infer.PrecisionType."""
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


def get_version() -> str:
    """Parity: paddle_infer.get_version."""
    from .. import __version__
    return f"paddle_tpu inference {__version__}"


def get_num_bytes_of_data_type(dtype: "DataType") -> int:
    """Parity: paddle_infer.get_num_bytes_of_data_type."""
    sizes = {DataType.FLOAT32: 4, DataType.FLOAT16: 2, DataType.INT64: 8,
             DataType.INT32: 4, DataType.UINT8: 1, DataType.INT8: 1,
             DataType.BOOL: 1, DataType.BFLOAT16: 2, DataType.FLOAT64: 8}
    return sizes[DataType(dtype)]


def _get_phi_kernel_name(op_name: str) -> str:
    """Parity: inference/__init__.py _get_phi_kernel_name — maps a
    legacy op name to its phi kernel name.  Our dispatch already uses
    phi-style names, so this is mostly identity plus the historical
    renames the reference carries."""
    legacy = {"matmul_v2": "matmul", "elementwise_add": "add",
              "elementwise_mul": "multiply", "elementwise_sub": "subtract",
              "elementwise_div": "divide", "reduce_sum": "sum",
              "reduce_mean": "mean", "fill_constant": "full"}
    return legacy.get(op_name, op_name)


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """Parity: paddle.inference.convert_to_mixed_precision — rewrite a
    saved model's weights to a mixed-precision copy.  Operates on our
    jit.save artifacts: parameters are cast to the target dtype
    (bf16 by default on TPU), io dtypes preserved when keep_io_types."""
    import shutil
    import numpy as np
    from .. import framework_io
    target = "bfloat16"
    if mixed_precision in (PrecisionType.Half, "float16", "fp16"):
        target = "float16"
    state = framework_io.load(params_file)
    black = set(black_list or ())

    def cast(val):
        a = np.asarray(getattr(val, "_value", val))
        if np.issubdtype(a.dtype, np.floating):
            import jax.numpy as jnp
            return np.asarray(a, dtype=jnp.dtype(target))
        return a

    new_state = {k: (cast(v) if k not in black else v)
                 for k, v in state.items()}
    framework_io.save(new_state, mixed_params_file)
    if model_file and mixed_model_file and model_file != mixed_model_file:
        shutil.copy(model_file, mixed_model_file)


__all__ += ["DataType", "PlaceType", "PrecisionType", "get_version",
            "get_num_bytes_of_data_type", "_get_phi_kernel_name",
            "convert_to_mixed_precision"]
