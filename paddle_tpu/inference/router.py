"""Multi-engine serving router: one admission plane over N engines.

The "heavy traffic from millions of users" tier (ROADMAP item 5): a
:class:`ServingRouter` owns a pool of :class:`ContinuousBatchingEngine`
instances — heterogeneous configs allowed (mixed-step, tensor-parallel,
quantized, speculative; anything satisfying the small engine protocol
below) — and multiplies the per-engine work of rounds 6-14 by N engines
behind one front door.  Three responsibilities:

**Prefix-affinity routing.**  A request's routing key is the SAME chain
of block-granularity blake2b prompt-prefix digests the engines'
``PrefixPageCache`` registers pages under (``routing_keys``), so the
router can steer a request to the engine whose prefix set has the
longest match — its shared pages are ALREADY there, and admission turns
into a refcount bump + suffix-only prefill instead of a full prompt
recompute.  The match consults the engine's LIVE prefix table (ground
truth, eviction included) plus a bounded router-side record of prompts
recently routed there (so two same-prefix requests co-locate even while
the first is still prefilling).  No match -> least-loaded fallback: the
load score folds slot occupancy, KV-page utilization and prefill
chunk-queue depth — the same stats the observability gauges read,
scraped either in-process (``engine.health_payload()``) or over HTTP
from the round-9 ``/healthz`` endpoint (whose body now carries them as
JSON).

**SLO-aware admission.**  ``submit`` takes a per-request ``priority``
plus optional TTFT/TPOT targets — the TTFT target orders the queue
(earliest deadline first among equal priorities) and lets an
affinity-held request spill once its deadline passes; the TPOT target
shields a running request from preemption while an equal-priority
victim without one exists; the pending queue is BOUNDED
(``max_pending``, overflow raises :class:`RouterQueueFull` and counts
``outcome="rejected"``) and drains highest-priority-first (ties: the
earliest TTFT deadline, then FIFO).  When every healthy engine is full
and a pending request outranks some running one, the router preempts
the cheapest strictly-lower-priority victim through the engine's public
``preempt_request`` API — the refcounted ``free_sequence`` release
path, NOT victim truncation — and requeues it: the victim resumes on
whatever engine next has room, its already-generated tokens re-prefixed
onto the prompt.  Greedy decoding makes the resumed stream byte-
identical to an uninterrupted run (the bench gate).

**Failure handling.**  Every engine is probed each ``step()`` (payload
fetch by default, pluggable per handle); ``probe_failure_threshold``
consecutive failures — or an exception escaping ``engine.step()`` —
marks the engine unhealthy and DRAINS it: every in-flight request is
pulled off (via ``preempt_request`` while the engine's host state still
answers, else the router's own last-known token record) and requeued,
zero drops.  A recovered engine re-admits via ``recover_engine``.

**Request tracing + SLO attainment (round 16).**  The router owns a
bounded :class:`~paddle_tpu.observability.RequestTracer` (default ON;
``tracer=False`` drops to the no-op stub) recording every request's
typed phase chain — enqueue, affinity-hold rounds, the route decision
(engine + prefix/least-loaded/spilled/random outcome), dispatch,
first token, preempt/requeue/engine-lost hops with the destination
engine, finish — and keeps per-hop ``(engine, engine_req_id,
t_dispatch, t_leave)`` records so
:func:`~paddle_tpu.observability.fleet_trace` can merge the router's
and every engine's spans into ONE chrome trace with flow arrows
across engines.  At completion the measured TTFT (submit -> first
token, across requeues) and mean TPOT are judged against the request's
declared targets — ``router_slo_attained_total{kind,outcome}`` — and
fed into bounded reservoirs whose p50/p95/p99 digests surface in
:meth:`ServingRouter.health_payload` (wire it to ``/healthz`` via
``set_health_provider``) and the
``router_latency_quantile_seconds{kind,q}`` gauges; the same summary
is attached to each finished record (``RouterRequest.summary``) so
streaming drivers read the numbers off ``pop_record`` without
scraping metrics.

**KV page migration + disaggregation (round 19).**  Requests no longer
lose their KV when they move: every preempt/drain path tries the
engine's ``extract_request`` first — the sequence's physical pages
(int8 codes + per-page scale rows) serialize to ONE host buffer per
dtype — and the next dispatch tries ``inject_request``, scattering
them into the target pool in one donated dispatch so the stream
resumes with ZERO re-prefill (geometry mismatch degrades to the r15
re-prefill resume).  Pools mixing engine ``role``\\ s get disaggregated
dispatch: fresh prompts route to ``role="prefill"`` specialists (big
token budgets), and once a request's prefill completes there the
``_migrate_ready`` sweep moves its pages to a ``role="decode"``
specialist (high slot counts, int8 KV) — TTFT is paid on the prefill
tier, TPOT is isolated on the decode tier.  All-"mixed" pools (the
default) behave exactly as in r15.

**Capacity & efficiency plane (round 20, ``capacity=``).**  A router
built with ``capacity=True`` (or a
:class:`~paddle_tpu.observability.CapacityConfig`) samples every
probe-refreshed engine payload into bounded per-engine
``SignalWindow``\\ s once per step — rolling tokens/s, admission and
preempt rates, queue-depth growth, prefix-hit-rate drift, host-tier
spill/restore pressure, saturation EWMA — and folds the fleet rollup
through a hysteresis + minimum-dwell planner into an advisory action
(``scale_up`` / ``scale_down`` / ``rebalance`` / ``steady``) exposed
via :meth:`ServingRouter.capacity_plan`,
``health_payload()["capacity"]`` (hence ``/healthz``) and the
``router_capacity_*`` metrics; per-engine serving-step MFU and
HBM-bytes/token gauges ride the same plane off the cached compiled
steps' ``cost_analysis``.  Pure advisory — the actuation (admit/drain
engines, live resharding) is ROADMAP item 5's next PR.  Default off:
an unconfigured router runs the exact r19 step loop.

Engine protocol (what a pool member must provide): ``add_request(
prompt_ids, max_new_tokens=, eos_token_id=)`` appending to ``waiting``,
``step() -> finished req_ids``, ``has_work()``, ``finished`` dict,
``preempt_request(req_id)``, ``health_payload()``, ``block_size``, and
optionally ``prefix_cache``/``engine_id``/``tracer``/``role``/
``extract_request``/``inject_request`` — i.e. the public surface of
``ContinuousBatchingEngine``.

All router state is host control flow: no device math, no new compiled
modules — the engines' one-compile invariants are untouched.
"""
from __future__ import annotations

import itertools
import json as _json
import time
import urllib.request
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .fleet import EngineRPCError, RetryPolicy
from .prefix_cache import _prefix_key

__all__ = ["ServingRouter", "EngineHandle", "RouterRequest",
           "RouterQueueFull", "routing_keys", "load_score"]


class RouterQueueFull(RuntimeError):
    """``submit`` refused: the bounded pending queue is at capacity."""


# fallback ids for pool members that don't carry an ``engine_id``
# attribute (the protocol lists it as optional): drawn from a high
# base so they never collide with explicit small ids
_FALLBACK_ENGINE_IDS = itertools.count(1 << 30)


def routing_keys(prompt_ids, block_size: int) -> List[bytes]:
    """The request's routing-key chain: blake2b digests of the token
    prefix up to each full page boundary — EXACTLY the keys
    ``PrefixPageCache`` registers pages under, so a key present in an
    engine's table means that engine already holds the KV pages for
    that prefix."""
    prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
    return [_prefix_key(prompt_ids, (i + 1) * block_size)
            for i in range(len(prompt_ids) // block_size)]


def load_score(payload: Dict) -> float:
    """Scalar load from a health payload — lower is better::

        (occupancy + waiting) / slots        # slot pressure
        + 1 - free_pages / total_pages       # KV-page utilization
        + chunk_queue_depth / slots          # prefill backlog

    Each term is O(1)-ish in [0, ~1] so no single axis dominates;
    missing fields read as unloaded (a thin healthz responder still
    routes sanely)."""
    slots = max(1, int(payload.get("slots", 1)))
    total = max(1, int(payload.get("total_pages", 1)))
    free = float(payload.get("free_pages", total))
    return ((float(payload.get("occupancy", 0))
             + float(payload.get("waiting", 0))) / slots
            + 1.0 - free / total
            + float(payload.get("chunk_queue_depth", 0)) / slots)


@dataclass
class RouterRequest:
    """One request as the ROUTER tracks it — the authoritative record
    that survives engine loss: the original prompt, every token any
    engine generated for it (``base_output`` after a requeue), and the
    SLO fields admission orders on."""
    rid: int
    prompt_ids: np.ndarray
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    priority: int = 0
    ttft_target: Optional[float] = None
    tpot_target: Optional[float] = None
    state: str = "pending"          # pending -> dispatched -> done
    engine_id: int = -1
    engine_req_id: int = -1
    engine_req: object = field(default=None, repr=False)
    # tokens generated on PREVIOUS engines (re-prefixed on requeue);
    # output_ids is the final base + last engine's stream
    base_output: List[int] = field(default_factory=list)
    output_ids: List[int] = field(default_factory=list)
    requeues: int = 0
    truncated: bool = False
    routed_by_prefix: bool = False
    # router rounds this request was HELD for a full affinity target
    # (bounded by affinity_wait_steps before spilling to least-loaded)
    affinity_waited: int = 0
    # engines whose add_request rejected this request (ValueError:
    # pages / block-table geometry).  The rejection is static for a
    # given prompt length and only tightens as the resume prompt
    # grows, so these engines are excluded from ranking AND from
    # preemption — preempting a victim on an engine that cannot hold
    # this request would be pure churn
    rejected_engines: set = field(default_factory=set)
    # round 19: the KV pages extracted off the engine this request
    # last ran on (a host KVPageBuffer) — the next dispatch tries
    # inject_request first, resuming with ZERO re-prefill; dropped
    # after any successful dispatch (tokens then outgrow its coverage)
    kv_buffer: object = field(default=None, repr=False)
    # prefill→decode page migrations (the disaggregated-serving hop;
    # these also count one requeue each, reason="migrated")
    migrations: int = 0
    # routing-key chains memoized per block size (hashing the prompt
    # prefix chain is O(L^2/bs) bytes — computing it once per resume
    # prompt instead of per engine per round keeps ranking cheap);
    # cleared on requeue, when the resume prompt grows
    key_cache: Dict[int, List[bytes]] = field(default_factory=dict,
                                              repr=False)
    # one entry per dispatch: [engine_id, engine_req_id, t_dispatch,
    # t_leave] (t_leave None while the segment is live) — the hop
    # record fleet_trace draws cross-engine flow arrows from and the
    # summary's engines_visited reads
    hops: List[list] = field(default_factory=list, repr=False)
    # final per-request numbers (ttft, mean_tpot, requeues,
    # engines_visited, slo outcomes), set at completion — streaming
    # drivers read these off the finished record instead of scraping
    # process-wide metrics
    summary: Optional[Dict] = None
    t_submit: float = 0.0
    # pending-phase start: t_submit, then each requeue mark (the
    # tracer's pending spans must tile requeue waits too)
    t_requeued: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0

    def engines_visited(self) -> List[int]:
        return [h[0] for h in self.hops]

    def resume_prompt(self) -> np.ndarray:
        """Prompt for (re-)admission: original tokens plus everything
        already generated — a greedy engine prefilling this emits the
        exact continuation the preempted stream would have."""
        if not self.base_output:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids,
             np.asarray(self.base_output, np.int64)])

    def remaining_budget(self) -> int:
        return self.max_new_tokens - len(self.base_output)

    def deadline(self) -> float:
        # `is not None`: ttft_target=0.0 is the MOST urgent deadline
        # (now), not the absence of one
        return self.t_submit + (self.ttft_target
                                if self.ttft_target is not None
                                else float("inf"))

    def routing_keys_for(self, block_size: int) -> List[bytes]:
        keys = self.key_cache.get(block_size)
        if keys is None:
            keys = routing_keys(self.resume_prompt(), block_size)
            self.key_cache[block_size] = keys
        return keys


class EngineHandle:
    """One pool member: the engine (or a URL to scrape it), health
    state, and the router-side prefix-affinity record."""

    # bounded complement of the engine's live prefix table: keys of
    # prompts ROUTED here whose prefill hasn't registered pages yet
    MAX_ROUTED_KEYS = 4096

    def __init__(self, engine, engine_id: Optional[int] = None,
                 health_url: Optional[str] = None,
                 probe: Optional[Callable[["EngineHandle"], bool]] = None,
                 probe_timeout: float = 1.0,
                 retry: Optional[RetryPolicy] = None):
        self.engine = engine
        if engine_id is None:
            engine_id = getattr(engine, "engine_id", None)
        if engine_id is None:
            engine_id = next(_FALLBACK_ENGINE_IDS)
        self.engine_id = int(engine_id)
        self.health_url = health_url
        self._probe = probe
        # remote scrapes run INSIDE the router step loop, serialized:
        # a partitioned endpoint stalls every healthy engine's round
        # for this long per probe, so keep it tight (a slow-but-alive
        # engine that misses it just accrues probe_failures and drains
        # — requests resume elsewhere, nothing is lost)
        self.probe_timeout = float(probe_timeout)
        # /healthz scraping shares the fleet RPC layer's capped-
        # backoff-with-jitter policy: one slow/lost scrape retries
        # inside the probe instead of burning a probe-failure count.
        # The whole retried scrape stays bounded (attempts x timeout
        # + backoff), so a dead endpoint still fails the probe fast.
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, base_delay=0.02, max_delay=0.25)
        self.healthy = True
        self.probe_failures = 0
        self.routed_keys: "OrderedDict[bytes, None]" = OrderedDict()
        # refreshed once per router step; dispatch adjusts it locally
        # as it places work so later picks in the same step see the load
        self.last_payload: Dict = {}

    # ---- load ----------------------------------------------------------
    def payload(self) -> Dict:
        """Fresh health/load stats: scraped from ``health_url``'s
        ``/healthz`` JSON body when remote, else read in-process."""
        if self.health_url:
            def _scrape():
                with urllib.request.urlopen(
                        self.health_url,
                        timeout=self.probe_timeout) as resp:
                    return _json.loads(resp.read().decode("utf-8"))
            # urllib.error.URLError is an OSError: the default
            # retry_on covers timeouts, refused and reset connections
            return self.retry.run(_scrape)
        return self.engine.health_payload()

    def refresh(self) -> Dict:
        self.last_payload = self.payload()
        return self.last_payload

    def load(self) -> float:
        return load_score(self.last_payload)

    def has_capacity(self) -> bool:
        p = self.last_payload
        slots = max(1, int(p.get("slots", 1)))
        return (int(p.get("occupancy", 0))
                + int(p.get("waiting", 0))) < slots

    def note_dispatched(self):
        self.last_payload["waiting"] = \
            int(self.last_payload.get("waiting", 0)) + 1

    # ---- health --------------------------------------------------------
    def probe(self) -> bool:
        """One liveness/health check.  Default: the payload fetch
        itself — an engine whose stats cannot be read cannot be routed
        to.  Pluggable per handle for deployments with richer checks;
        a passing custom probe still refreshes the load payload (the
        ranking/capacity signals live there — a routable engine must
        also be readable)."""
        if self._probe is not None:
            try:
                if not self._probe(self):
                    return False
            except Exception:                         # noqa: BLE001
                return False
        try:
            self.refresh()
            return True
        except Exception:                             # noqa: BLE001
            return False

    # ---- prefix affinity -----------------------------------------------
    def prefix_match_tokens(self, prompt_ids, keys=None) -> int:
        """Longest consecutive run of the prompt's routing keys present
        on this engine, in TOKENS (block sizes differ across a
        heterogeneous pool, so token counts are the comparable unit).
        Engines without a prefix cache never match — affinity would buy
        nothing where pages cannot be shared.  ``keys`` takes a
        precomputed chain for this engine's block size (the router
        memoizes it per request — hashing is O(L^2/bs) bytes)."""
        bs = getattr(self.engine, "block_size", 0)
        pc = getattr(self.engine, "prefix_cache", None)
        if not bs or pc is None:
            return 0
        live = pc.table
        n = 0
        for key in (keys if keys is not None
                    else routing_keys(prompt_ids, bs)):
            if key in live or key in self.routed_keys:
                n += 1
            else:
                break
        return n * bs

    def note_routed(self, prompt_ids, keys=None):
        """Record the routed prompt's keys so same-prefix requests
        co-locate before the first prefill registers pages (the live
        table takes over once it does; stale records age out FIFO)."""
        bs = getattr(self.engine, "block_size", 0)
        if not bs or getattr(self.engine, "prefix_cache", None) is None:
            return
        if keys is None:
            keys = routing_keys(prompt_ids, bs)
        for key in keys:
            self.routed_keys[key] = None
            self.routed_keys.move_to_end(key)
        while len(self.routed_keys) > self.MAX_ROUTED_KEYS:
            self.routed_keys.popitem(last=False)


class ServingRouter:
    """N continuous-batching engines behind one admission plane.

    ``engines``: iterable of engines or pre-built :class:`EngineHandle`
    (build handles yourself to attach ``health_url``/custom probes).
    ``route_policy``: ``"affinity"`` (default: prefix match, then
    least-loaded) or ``"random"`` (seeded uniform over engines with
    capacity — the bench's control arm).  ``preempt=False`` disables
    priority preemption (pending requests then only wait).

    The driving loop mirrors a single engine's: ``submit`` any time,
    ``step()`` advances every healthy engine one round, ``result`` after
    the rid shows up in a step's finished list (or ``run_to_completion``
    for batch use).
    """

    def __init__(self, engines, max_pending: int = 256,
                 preempt: bool = True,
                 probe_failure_threshold: int = 1,
                 route_policy: str = "affinity",
                 route_seed: int = 0,
                 affinity_wait_steps: int = 8,
                 max_finished: int = 4096,
                 tracer=None,
                 capacity=None):
        if route_policy not in ("affinity", "random"):
            raise ValueError(
                "route_policy must be 'affinity' or 'random'; got %r"
                % (route_policy,))
        self.handles: "OrderedDict[int, EngineHandle]" = OrderedDict()
        for e in engines:
            h = e if isinstance(e, EngineHandle) else EngineHandle(e)
            if h.engine_id in self.handles:
                raise ValueError(
                    "duplicate engine_id %d in the pool — pass distinct "
                    "engine_id= to the engines (or handles)"
                    % h.engine_id)
            self.handles[h.engine_id] = h
        if not self.handles:
            raise ValueError("ServingRouter needs at least one engine")
        self.max_pending = int(max_pending)
        self.preempt = bool(preempt)
        self.probe_failure_threshold = max(1, int(probe_failure_threshold))
        self.route_policy = route_policy
        self._route_rng = np.random.RandomState(route_seed)
        # a request whose longest prefix match sits on a FULL engine is
        # HELD (its pages are there; waiting one slot-drain usually
        # beats recomputing the prefix elsewhere) — but only this many
        # router rounds, then it spills to least-loaded, recomputes,
        # and REGISTERS the prefix there too (a hot family replicates
        # itself across the pool instead of head-of-line blocking)
        self.affinity_wait_steps = max(0, int(affinity_wait_steps))
        # disaggregated serving (round 19): pools mixing engine roles
        # get role-aware ranking (fresh prompts avoid decode
        # specialists, resumed/migrated requests avoid prefill
        # specialists), and a prefill+decode pool runs the
        # prefill→decode page-migration sweep each step.  All-"mixed"
        # pools (the default) see neither — r15 behavior untouched.
        self._refresh_roles()
        self.pending: List[RouterRequest] = []
        # bounded completed-request record (a long-running admission
        # plane must not grow without bound): oldest completions are
        # evicted past ``max_finished`` — batch callers either keep
        # a wave under that, consume via pop_result, or raise the cap
        self.max_finished = max(1, int(max_finished))
        self.finished: "OrderedDict[int, RouterRequest]" = OrderedDict()
        # (engine_id, engine_req_id) -> RouterRequest for every
        # dispatched, unfinished request — the drain walks this
        self._inflight: Dict[Tuple[int, int], RouterRequest] = {}
        # every _complete lands its rid here; step() drains it as the
        # return value, so completions that happen OUT OF BAND (a
        # requeue that already met its budget, a mark_unhealthy drain
        # between steps) surface in the next step's list instead of
        # going missing
        self._done_backlog: List[int] = []
        self._next_rid = 0

        from ..observability import default_registry
        from ..observability.capacity import resolve_capacity_monitor
        from ..observability.request_trace import (LatencyReservoir,
                                                   resolve_tracer)
        # fleet capacity & efficiency plane (round 20): OFF by default
        # — an unconfigured router runs the exact r19 step loop (the
        # bench's defaults-parity gate).  capacity=True (or a
        # CapacityConfig / prebuilt FleetCapacityMonitor) samples every
        # probe-refreshed payload into per-engine SignalWindows once
        # per step and ticks the hysteresis+dwell planner behind
        # ``capacity_plan()`` / ``health_payload()["capacity"]``.
        self.capacity = resolve_capacity_monitor(capacity)
        # bounded per-request phase tracer (round 16): default ON —
        # host-side appends only; tracer=False drops to the no-op stub
        self.tracer = resolve_tracer(tracer)
        # measured-latency reservoirs behind the p50/p95/p99 digests in
        # health_payload() and the quantile gauges
        self._ttft_res = LatencyReservoir(1024, seed=1)
        self._tpot_res = LatencyReservoir(1024, seed=2)
        # per-ROUTER attainment counts (the Prometheus counters are
        # process-wide series shared across routers; the completeness
        # gate sums THESE against this router's own admissions)
        self._slo_counts: Dict[Tuple[str, str], int] = {
            (k, o): 0 for k in ("ttft", "tpot")
            for o in ("attained", "missed", "no_target")}
        self._completions = 0
        r = default_registry()
        self._m_requests = r.counter(
            "router_requests_total",
            "requests leaving the router, by outcome (completed / "
            "truncated / rejected-at-the-bounded-queue)",
            labels=("outcome",))
        self._m_prefix_hits = r.counter(
            "router_prefix_route_hits_total",
            "dispatches steered by prefix affinity (the routed engine "
            "already held a nonzero prefix of the prompt)")
        self._m_requeues = r.counter(
            "router_requeues_total",
            "requests pulled off one engine and requeued, by reason "
            "(preempt / engine_lost / migrated — the prefill→decode "
            "disaggregation hop)", labels=("reason",))
        self._m_role_dispatch = r.counter(
            "router_role_dispatch_total",
            "dispatches by the target engine's role (prefill / decode "
            "/ mixed) — the disaggregated-serving placement split",
            labels=("role",))
        self._role_children = {
            role: self._m_role_dispatch.labels(role=role)
            for role in ("prefill", "decode", "mixed")}
        self._m_healthy = r.gauge(
            "router_engine_healthy",
            "1 while the router considers the engine routable, 0 after "
            "mark-unhealthy (probe failures or a step exception)",
            labels=("engine",))
        self._m_pending = r.gauge(
            "router_pending_depth",
            "requests admitted by the router but not yet dispatched "
            "to an engine")
        self._m_slo = r.counter(
            "router_slo_attained_total",
            "completed requests judged against their declared SLO "
            "targets, by kind (ttft / tpot) and outcome (attained / "
            "missed / no_target) — for each kind the outcomes sum to "
            "completed admissions",
            labels=("kind", "outcome"))
        # resolve the six children once (completion-path, but labels()
        # is a lock + probe and the label sets are closed anyway)
        self._slo_children = {
            (k, o): self._m_slo.labels(kind=k, outcome=o)
            for k in ("ttft", "tpot")
            for o in ("attained", "missed", "no_target")}
        self._m_latency_q = r.gauge(
            "router_latency_quantile_seconds",
            "bounded-reservoir latency digests over completed requests "
            "(kind: ttft / tpot; q: p50 / p95 / p99)",
            labels=("kind", "q"))
        self._latq_children = {
            (k, q): self._m_latency_q.labels(kind=k, q=q)
            for k in ("ttft", "tpot") for q in ("p50", "p95", "p99")}
        self._m_pool = r.gauge(
            "router_engine_pool_size",
            "engines currently admitted to the router's pool (healthy "
            "or not) — the elastic actuator's scale_up/scale_down is "
            "what moves this")
        for h in self.handles.values():
            self._m_healthy.labels(engine=str(h.engine_id)).set(1)
        self._m_pool.set(len(self.handles))

    def _refresh_roles(self):
        """Recompute the role-aware dispatch flags — in __init__ and on
        every pool-membership change (an all-'mixed' pool must keep the
        exact r15 ranking even after engines come and go)."""
        roles = [getattr(h.engine, "role", "mixed")
                 for h in self.handles.values()]
        self._role_pool = any(r != "mixed" for r in roles)
        self._disagg = ("prefill" in roles
                        and any(r != "prefill" for r in roles))

    # ---- elastic pool membership ----------------------------------------
    def add_engine(self, engine) -> int:
        """Admit one engine (or pre-built :class:`EngineHandle`) to the
        live pool — the elastic actuator's scale_up.  The newcomer is
        routable from the next ``step()``: it probes, ranks (its empty
        slots make it the least-loaded target), and samples into the
        capacity plane like any founding member.  Returns its
        engine_id; a duplicate id raises ValueError."""
        h = engine if isinstance(engine, EngineHandle) \
            else EngineHandle(engine)
        if h.engine_id in self.handles:
            raise ValueError(
                "duplicate engine_id %d in the pool — pass a distinct "
                "engine_id= on the engine (or handle)" % h.engine_id)
        self.handles[h.engine_id] = h
        h.healthy = True
        h.probe_failures = 0
        self._refresh_roles()
        self._m_healthy.labels(engine=str(h.engine_id)).set(1)
        self._m_pool.set(len(self.handles))
        return h.engine_id

    def remove_engine(self, engine_id: int,
                      reason: str = "scale_down") -> Dict[str, int]:
        """Retire one engine from the pool — the elastic actuator's
        scale_down.  Every in-flight request drains off it first
        through the same extract-first requeue the failure path uses
        (KV pages travel, the resume injects with zero re-prefill),
        but with ``reason="scale_down"``: a planned retirement is not
        an ``engine_lost``.  The handle then leaves the pool entirely
        (a removed engine is gone, not parked-unhealthy).  Returns the
        drain fate counts ``{"migrated": n, "re_prefilled": m}``.
        Removing the last engine raises ValueError — a router must
        keep at least one."""
        if engine_id not in self.handles:
            raise KeyError("engine %r is not in the pool" % (engine_id,))
        if len(self.handles) <= 1:
            raise ValueError(
                "refusing to remove the last engine in the pool")
        h = self.handles[engine_id]
        fates = self._drain_engine(h, reason=reason)
        del self.handles[engine_id]
        if self.capacity is not None:
            # its frozen windows must leave the rollup too, or the
            # planner would keep averaging a ghost engine forever
            self.capacity.drop_engine(engine_id)
        self._refresh_roles()
        self._m_healthy.labels(engine=str(engine_id)).set(0)
        self._m_pool.set(len(self.handles))
        return fates

    # ---- public API -----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int = 16,
               eos_token_id: Optional[int] = None, priority: int = 0,
               ttft_target: Optional[float] = None,
               tpot_target: Optional[float] = None) -> int:
        """Queue one prompt with its SLO envelope; returns the router
        request id.  ``ttft_target`` (seconds) orders the pending
        queue (earliest deadline first among equal priorities) and
        releases an affinity hold once the deadline passes;
        ``tpot_target`` marks the request preempt-last among
        equal-priority victims (a preemption is what blows a per-token
        SLO).  Raises :class:`RouterQueueFull` when the bounded
        pending queue is at ``max_pending`` (counted as
        ``outcome="rejected"`` — shed load at the front door instead
        of growing an unbounded backlog)."""
        if len(self.pending) >= self.max_pending:
            self._m_requests.labels(outcome="rejected").inc()
            raise RouterQueueFull(
                "pending queue at max_pending=%d" % self.max_pending)
        rr = RouterRequest(
            rid=self._next_rid,
            prompt_ids=np.asarray(prompt_ids, np.int64).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, priority=int(priority),
            ttft_target=ttft_target, tpot_target=tpot_target)
        self._next_rid += 1
        rr.t_submit = time.perf_counter()
        rr.t_requeued = rr.t_submit
        self.pending.append(rr)
        self._m_pending.set(len(self.pending))
        self.tracer.event(
            rr.rid, "enqueue", ts=rr.t_submit, priority=rr.priority,
            prompt_tokens=len(rr.prompt_ids),
            ttft_target=rr.ttft_target, tpot_target=rr.tpot_target)
        return rr.rid

    def has_work(self) -> bool:
        return bool(self.pending) or bool(self._inflight)

    def step(self) -> List[int]:
        """One router round: probe every engine, dispatch pending work
        (preempting when priorities demand), then advance every healthy
        engine one ``step()``.  Returns every rid that completed since
        the last call — including out-of-band completions (a requeue
        whose tokens already met the budget, a ``mark_unhealthy`` drain
        between steps): callers keying on the returned ids must never
        have one go missing."""
        self._probe_all()
        self._dispatch_pending()
        # remote-engine fan-out: fire every step RPC BEFORE collecting
        # any reply, so N server processes genuinely step concurrently
        # (begin_step is an opportunistic send — failures surface in
        # the per-handle step()/finish below and take the engine-lost
        # path there)
        for h in self.handles.values():
            begin = getattr(h.engine, "begin_step", None)
            if begin is not None and h.healthy and h.engine.has_work():
                begin()
        for h in list(self.handles.values()):
            if not h.healthy:
                continue
            try:
                if h.engine.has_work():
                    for erid in h.engine.step():
                        rr = self._inflight.pop((h.engine_id, erid),
                                                None)
                        if rr is not None:
                            # pop, don't read: the router holds the
                            # authoritative copy, and the engine-side
                            # record would otherwise grow per request
                            # forever in a long-running deployment
                            self._complete(
                                rr, h.engine.finished.pop(erid))
            except Exception:                         # noqa: BLE001
                self._lose_engine(h)
                continue
            # defensive sweep — OUTSIDE the has_work gate: anything of
            # ours in the engine's finished dict that a step() return
            # ever missed (an engine implementation quirk must degrade
            # to a late completion, never to a request the router
            # waits on forever, even once the engine has gone idle)
            for key in [k for k in self._inflight
                        if k[0] == h.engine_id
                        and k[1] in h.engine.finished]:
                rr = self._inflight.pop(key)
                self._complete(rr, h.engine.finished.pop(key[1]))
            self._sync_first_tokens(h)
        if self._disagg:
            self._migrate_ready()
        if self.capacity is not None:
            # one sampling + planner tick per router round, fed from
            # the payloads _probe_all already refreshed (zero extra
            # scrapes; O(1) window appends per engine)
            self.capacity.observe_router(self)
        self._m_pending.set(len(self.pending))
        done, self._done_backlog = self._done_backlog, []
        return done

    def run_to_completion(self) -> Dict[int, List[int]]:
        stalled = 0
        while self.has_work():
            if not any(h.healthy for h in self.handles.values()):
                raise RuntimeError(
                    "ServingRouter: no healthy engines left with %d "
                    "request(s) outstanding — recover_engine() or add "
                    "capacity" % (len(self.pending) + len(self._inflight)))
            n_pending = len(self.pending)
            self.step()
            if (self.pending and not self._inflight
                    and len(self.pending) == n_pending
                    and not any(h.healthy and h.engine.has_work()
                                for h in self.handles.values())):
                # nothing in flight, every engine idle, dispatch placed
                # nothing.  One such step is normal (the engine drained
                # DURING it, after dispatch ran); two in a row means
                # these requests fit NO engine in the pool
                # (pages/block-table limits) — fail loudly, don't spin
                stalled += 1
                if stalled >= 2:
                    raise RuntimeError(
                        "ServingRouter: %d pending request(s) fit no "
                        "engine in the pool (add_request rejected them "
                        "everywhere)" % len(self.pending))
            else:
                stalled = 0
        return {rid: r.output_ids for rid, r in self.finished.items()}

    def result(self, rid: int) -> List[int]:
        return self.finished[rid].output_ids

    def pop_result(self, rid: int) -> List[int]:
        """Consume one finished request's tokens (the streaming-driver
        API: read each rid from ``step()``'s return, pop it, and the
        finished record stays flat regardless of run length).  Drivers
        that also want the latency numbers use :meth:`pop_record`."""
        return self.finished.pop(rid).output_ids

    def pop_record(self, rid: int) -> RouterRequest:
        """Consume one finished request's FULL record: tokens in
        ``.output_ids`` plus the final per-request summary in
        ``.summary`` (measured ttft, mean tpot, requeue count, engines
        visited, SLO outcomes) — streaming drivers get the numbers
        without scraping process-wide metrics.  Same bounded-`finished`
        eviction semantics as :meth:`pop_result`."""
        return self.finished.pop(rid)

    def _publish_latency_gauges(self, digests: Optional[Dict] = None):
        """Push the reservoir digests into the
        ``router_latency_quantile_seconds{kind,q}`` gauges."""
        for kind, res in (("ttft", self._ttft_res),
                          ("tpot", self._tpot_res)):
            d = (digests or {}).get(kind) or res.digest()
            for tag in ("p50", "p95", "p99"):
                if d[tag] is not None:
                    self._latq_children[(kind, tag)].set(d[tag])

    def slo_snapshot(self) -> Dict[str, Dict]:
        """Per-kind attainment counts + bounded-reservoir latency
        digests (p50/p95/p99) over THIS router's completed requests —
        the ``health_payload()``/``/healthz`` SLO block, and the
        completeness gate's arithmetic source (for each kind the
        outcome counts sum to completed admissions).  Reading a
        snapshot also refreshes the quantile gauges, so a Prometheus
        scrape taken through any health path is exact."""
        out = {}
        for kind, res in (("ttft", self._ttft_res),
                          ("tpot", self._tpot_res)):
            d = {o: self._slo_counts[(kind, o)]
                 for o in ("attained", "missed", "no_target")}
            d.update(res.digest())
            out[kind] = d
        self._publish_latency_gauges(out)
        return out

    def capacity_plan(self) -> Dict:
        """The committed fleet capacity recommendation — windowed
        per-engine signals, fleet rollup, and the advisory action
        (``scale_up`` / ``scale_down`` / ``rebalance`` / ``steady``)
        with the declared hysteresis bands + minimum dwell already
        applied, so an actuator can follow it verbatim without its own
        debouncing (ROADMAP item 5's consumer).  Requires capacity
        monitoring: construct with ``capacity=True`` (or a
        ``CapacityConfig`` / prebuilt ``FleetCapacityMonitor``)."""
        if self.capacity is None:
            raise ValueError(
                "capacity monitoring is off: construct ServingRouter("
                "capacity=True) (or pass a CapacityConfig / "
                "FleetCapacityMonitor) to enable capacity_plan()")
        return self.capacity.capacity_plan()

    def health_payload(self) -> Dict:
        """Fleet-level load/health snapshot (the router-side twin of
        the engine's ``health_payload``): queue depths, healthy-engine
        count, the SLO attainment digests, and — when capacity
        monitoring is configured — the committed capacity plan.
        Install as the process's health provider
        (``observability.set_health_provider(router.health_payload)``)
        and ``/healthz`` serves it."""
        payload = {
            "router": 1,
            "pending": len(self.pending),
            "inflight": len(self._inflight),
            "engines": len(self.handles),
            "engines_healthy": sum(1 for h in self.handles.values()
                                   if h.healthy),
            "slo": self.slo_snapshot(),
        }
        if self.capacity is not None:
            payload["capacity"] = self.capacity.capacity_plan()
        return payload

    # ---- health ---------------------------------------------------------
    def mark_unhealthy(self, engine_id: int):
        """Operator/test hook: take an engine out of rotation NOW and
        drain-and-requeue everything in flight on it (the same path a
        failed probe or step exception takes)."""
        self._lose_engine(self.handles[engine_id])

    def recover_engine(self, engine_id: int):
        """Re-admit an engine (restarted, or past a transient probe
        blip).  Its router-side affinity record was cleared on loss;
        matching restarts from its LIVE prefix table, which is exactly
        right for both a fresh restart (empty) and a survivor (intact)."""
        h = self.handles[engine_id]
        h.healthy = True
        h.probe_failures = 0
        self._m_healthy.labels(engine=str(h.engine_id)).set(1)

    def _probe_all(self):
        for h in self.handles.values():
            if not h.healthy:
                continue
            if h.probe():
                h.probe_failures = 0
            else:
                h.probe_failures += 1
                if h.probe_failures >= self.probe_failure_threshold:
                    self._lose_engine(h)

    def _lose_engine(self, h: EngineHandle):
        """Mark unhealthy + drain: every in-flight request comes off
        through ``preempt_request`` when the engine's host state still
        answers (refcounted release — a later recovery finds a clean
        pool), else from the router's own record; all requeue with
        reason="engine_lost".  Zero drops by construction: every
        dispatched request is in ``_inflight`` until completed."""
        if not h.healthy:
            return
        h.healthy = False
        h.probe_failures = 0
        self._m_healthy.labels(engine=str(h.engine_id)).set(0)
        self._drain_engine(h, reason="engine_lost")

    def _drain_engine(self, h: EngineHandle,
                      reason: str) -> Dict[str, int]:
        """The one drain body (failure path AND planned scale_down):
        pull every in-flight request off ``h`` extract-first and
        requeue it with ``reason``.  Returns how each drained request
        travels: ``"migrated"`` (its KV pages came with it — the
        resume injects, zero re-prefill) vs ``"re_prefilled"``
        (extraction unsupported/failed; the r15 recompute resume)."""
        fates = {"migrated": 0, "re_prefilled": 0}
        h.routed_keys.clear()
        for (eid, erid) in [k for k in self._inflight
                            if k[0] == h.engine_id]:
            rr = self._inflight.pop((eid, erid))
            gen: List[int] = []
            vbuf = None
            try:
                # extract the victim's KV pages while the engine's
                # device state still answers — the requeued request
                # then resumes elsewhere with ZERO re-prefill; the
                # engine degrades extraction to buffer=None itself
                # when its pools can't travel
                ext = getattr(h.engine, "extract_request", None)
                if ext is not None:
                    _prompt, gen, vbuf = ext(erid)
                else:
                    _prompt, gen = h.engine.preempt_request(erid)
            except Exception:                         # noqa: BLE001
                # the request finished INSIDE the failing step, or the
                # engine is too far gone: consume the engine-side
                # finished record if there is one (popping it — a
                # recovered engine must not strand it forever), else
                # fall back to the live request object's token list
                ereq = None
                try:
                    ereq = h.engine.finished.pop(erid, None)
                except Exception:                     # noqa: BLE001
                    pass
                try:
                    gen = list((ereq or rr.engine_req).output_ids)
                except Exception:                     # noqa: BLE001
                    gen = []
            fates["migrated" if vbuf is not None
                  else "re_prefilled"] += 1
            self._requeue(rr, gen, reason=reason, buffer=vbuf)
        return fates

    # ---- requeue / preemption -------------------------------------------
    def _requeue(self, rr: RouterRequest, gen: List[int], reason: str,
                 buffer=None):
        """Fold the tokens the lost/preempted engine generated into the
        router-side record and put the request back in the pending
        queue (or finish it, if those tokens already met the budget or
        hit EOS).  ``buffer`` carries the KV pages extracted off the
        engine being left (a host ``KVPageBuffer``): the next dispatch
        injects them into the target pool and the request resumes with
        zero re-prefill; None (extraction unsupported or failed)
        degrades to the r15 re-prefill resume."""
        # the first token may have landed on the engine we are leaving
        # without a _sync_first_tokens pass seeing it (preempt/loss
        # between steps): capture its mark off the live engine request
        # BEFORE dropping it, or the measured TTFT would drift to the
        # completion fallback
        if not rr.t_first_token and (gen or rr.base_output):
            t_ft = getattr(rr.engine_req, "t_first_token", 0.0) or 0.0
            rr.t_first_token = t_ft or time.perf_counter()
            self.tracer.event(rr.rid, "first_token",
                              ts=rr.t_first_token,
                              ttft=rr.t_first_token - rr.t_submit)
        now = time.perf_counter()
        left_engine = -1
        if rr.hops and rr.hops[-1][3] is None:
            rr.hops[-1][3] = now
            left_engine = rr.hops[-1][0]
            self.tracer.span(rr.rid, "on_engine", rr.hops[-1][2], now,
                             engine=left_engine)
        rr.t_requeued = now
        rr.base_output.extend(int(t) for t in gen)
        rr.key_cache.clear()            # resume prompt just grew
        # a fresh extraction replaces any stale buffer; extraction
        # failure (None) must also clear it — old pages no longer
        # cover the grown resume prompt
        rr.kv_buffer = buffer
        rr.engine_id = -1
        rr.engine_req_id = -1
        rr.engine_req = None
        rr.requeues += 1
        self.tracer.event(rr.rid, "requeue", ts=now, reason=reason,
                          engine=left_engine, tokens=len(gen))
        self._m_requeues.labels(reason=reason).inc()
        hit_eos = (rr.eos_token_id is not None and rr.base_output
                   and rr.base_output[-1] == rr.eos_token_id)
        if rr.remaining_budget() <= 0 or hit_eos:
            self._complete(rr, None)
            return
        rr.state = "pending"
        self.pending.append(rr)

    def _preempt_and_place(self, rr: RouterRequest,
                           only: Optional[EngineHandle] = None) -> bool:
        """Every engine ``rr`` may use is full and it outranks someone:
        place ``rr`` by preempting the cheapest strictly-lower-priority
        running request (lowest priority first; among equals, requests
        WITHOUT a TPOT target before those with one — a preemption is
        exactly what blows a per-token-latency SLO — then fewest total
        tokens, the smallest re-prefix bill).  ``rr`` is dispatched to
        the victim's engine FIRST (engine queues accept regardless of
        slot occupancy — capacity gating is the router's own notion),
        and the victim is pulled only once that succeeds: an engine
        whose geometry rejects ``rr`` costs a recorded rejection, never
        a pointless preemption.  ``only`` restricts victims to one
        engine — when ``rr`` is holding for its affinity target, a
        preemption anywhere else would not place it."""
        victims = []
        for key, vr in self._inflight.items():
            h = self.handles[key[0]]
            if not h.healthy or vr.priority >= rr.priority:
                continue
            if h.engine_id in rr.rejected_engines:
                continue          # freeing a slot there cannot place rr
            if only is not None and h is not only:
                continue
            if getattr(vr.engine_req, "slot", 0) < 0:
                # dispatched but still in the engine's waiting queue:
                # pulling it frees NO slot — preempting it would strand
                # rr behind the same full slots
                continue
            try:
                n_tok = (len(vr.prompt_ids) + len(vr.base_output)
                         + len(vr.engine_req.output_ids))
            except Exception:                         # noqa: BLE001
                n_tok = len(vr.prompt_ids)
            victims.append(((vr.priority,
                             vr.tpot_target is not None, n_tok,
                             vr.rid), key, vr, h))
        tried = set()
        for _rank, key, vr, h in sorted(victims, key=lambda v: v[0]):
            if h.engine_id in tried:
                continue          # geometry already rejected rr there
            tried.add(h.engine_id)
            preempted_first = False
            if self._buffer_fits(rr, h):
                # rr carries extracted KV that fits this engine:
                # inject_request needs the slot FREE at dispatch time,
                # so pull the victim FIRST — otherwise every
                # preemption-path placement would burn the buffer on
                # the no-free-slot fallback and re-prefill anyway.
                # The geometry pre-check keeps the no-pointless-
                # preemption rule: the buffer is known to fit before
                # anyone is disturbed (a residual add_request
                # rejection after this can still waste one victim —
                # bounded by the rejected_engines memo)
                # a victim that raced to completion left its slot free
                # anyway — either way rr still needs the dispatch below
                self._pull_victim(key, vr, h)
                preempted_first = True
            if not self._dispatch(rr, h, self._match(h, rr)):
                continue
            if not preempted_first:
                if not self._pull_victim(key, vr, h):
                    return True   # raced with completion: slot free
                                  # anyway and rr is already queued
            try:
                h.refresh()
            except Exception:                         # noqa: BLE001
                # scrape died mid-round: take the engine-lost path
                # (rr just landed there and drains right back off)
                self._lose_engine(h)
            return True
        return False

    def _pull_victim(self, key, vr: RouterRequest,
                     h: EngineHandle) -> bool:
        """Preempt one victim off its engine and requeue it —
        extract-first, so its pages travel with it and its resume
        elsewhere skips the re-prefill bill that made preemption
        expensive.  Returns False when the victim raced to completion
        inside the engine (its slot is free regardless)."""
        try:
            ext = getattr(h.engine, "extract_request", None)
            if ext is not None:
                _prompt, gen, vbuf = ext(vr.engine_req_id)
            else:
                _prompt, gen = h.engine.preempt_request(
                    vr.engine_req_id)
                vbuf = None
        except EngineRPCError:
            # the victim's engine died under us: drain it (the victim
            # — and anything else in flight there — requeues off the
            # router's own record inside _lose_engine)
            self._lose_engine(h)
            return False
        except KeyError:
            return False
        self._inflight.pop(key, None)
        self._requeue(vr, gen, reason="preempt", buffer=vbuf)
        return True

    def _buffer_fits(self, rr: RouterRequest, h: EngineHandle) -> bool:
        """Does ``rr``'s extracted KV buffer match ``h``'s pool
        geometry?  The cheap pre-check behind preempt-before-dispatch
        and the disaggregation sweep — never extract or preempt for an
        inject that is known to fail."""
        buf = rr.kv_buffer
        if buf is None or not hasattr(h.engine, "inject_request"):
            return False
        geo = getattr(h.engine, "migration_geometry", None)
        if geo is None:
            return False
        try:
            return geo() == buf.geometry()
        except Exception:                             # noqa: BLE001
            return False

    # ---- disaggregated prefill→decode migration -------------------------
    def _migrate_ready(self):
        """The disaggregation sweep (pools mixing ``role="prefill"``
        and decode-side engines): any request whose prefill COMPLETED
        on a prefill specialist — it is decoding, its first token is
        out — has its KV pages extracted and requeues with
        ``reason="migrated"``; the next dispatch injects them into a
        decode-side engine (role-aware ranking steers it there) and
        the stream continues with zero re-prefill.  TTFT was already
        paid on the prefill specialist, so the move isolates decode
        TPOT from prefill interference without restarting anything.
        Only fires when a decode-side target currently has capacity —
        a full decode tier leaves the request where it runs."""
        for key in list(self._inflight.keys()):
            rr = self._inflight.get(key)
            if rr is None:
                continue
            h = self.handles.get(key[0])
            if h is None or not h.healthy:
                continue
            if getattr(h.engine, "role", "mixed") != "prefill":
                continue
            ereq = rr.engine_req
            if ereq is None or getattr(ereq, "state", "") != "running":
                continue
            if not getattr(ereq, "output_ids", None):
                continue
            # geometry pre-flight: only extract when the source CAN
            # produce a buffer and some decode-side target can take it
            # — otherwise the "migration" degrades to paying the
            # prefill twice (extract fails or inject rejects and the
            # resume re-prefills the whole prompt on the decode tier)
            src_geo = getattr(h.engine, "migration_geometry",
                              lambda: None)()
            if src_geo is None:
                continue
            if not any(t.healthy and t is not h
                       and getattr(t.engine, "role", "mixed") != "prefill"
                       and t.engine_id not in rr.rejected_engines
                       and t.has_capacity()
                       and getattr(t.engine, "migration_geometry",
                                   lambda: None)() == src_geo
                       for t in self.handles.values()):
                continue
            try:
                _prompt, gen, buf = h.engine.extract_request(key[1])
            except Exception:                         # noqa: BLE001
                continue
            self._inflight.pop(key, None)
            rr.migrations += 1
            self._requeue(rr, gen, reason="migrated", buffer=buf)

    # ---- dispatch -------------------------------------------------------
    def _match(self, h: EngineHandle, rr: RouterRequest) -> int:
        """Prefix-match tokens of ``rr`` on ``h``, through the
        request's memoized per-block-size key chain."""
        bs = getattr(h.engine, "block_size", 0)
        if not bs or getattr(h.engine, "prefix_cache", None) is None:
            return 0
        return h.prefix_match_tokens(None,
                                     keys=rr.routing_keys_for(bs))

    def _rank_engines(self, rr: RouterRequest
                      ) -> Tuple[List[Tuple[int, EngineHandle]],
                                 Optional[EngineHandle]]:
        """``(candidates best-first as (match_tokens, handle), hold)``.

        Affinity policy: the longest prefix match over every HEALTHY
        engine decides.  Match on an engine with capacity -> dispatch
        there (ties: load, then engine id).  Match only on FULL engines
        and the request hasn't exhausted its wait budget -> no
        candidates, ``hold`` names the engine worth waiting (or
        preempting) for.  No match (or wait exhausted, or TTFT deadline
        passed) -> least-loaded over capacity-holding engines.
        ``random`` policy shuffles the capacity-holding engines — the
        bench's control arm."""
        healthy = [h for h in self.handles.values()
                   if h.healthy and h.engine_id not in rr.rejected_engines]
        if self._role_pool:
            # disaggregated dispatch: fresh prompts go to prefill
            # specialists (and mixed), resumed/migrated requests to
            # decode specialists (and mixed).  Soft preference: when no
            # preferred engine has capacity the full healthy set stays
            # eligible — role policy must never strand a request a
            # mis-roled engine could serve
            # "fresh" = has no resumable state, so it needs a FULL
            # prefill wherever it lands (a victim preempted while
            # still waiting requeues with no tokens and no KV — it
            # belongs on the prefill tier despite its requeue count)
            fresh = not rr.base_output and rr.kv_buffer is None
            avoid = "decode" if fresh else "prefill"
            preferred = [h for h in healthy
                         if getattr(h.engine, "role", "mixed") != avoid]
            if any(h.has_capacity() for h in preferred):
                healthy = preferred
        cands = [h for h in healthy if h.has_capacity()]
        if self.route_policy == "random":
            order = self._route_rng.permutation(len(cands))
            return [(0, cands[i]) for i in order], None
        scored = [(self._match(h, rr), h) for h in healthy]
        best = max((m for m, _ in scored), default=0)
        if best > 0:
            matching = sorted(
                ((m, h) for m, h in scored if m == best),
                key=lambda mh: (mh[1].load(), mh[1].engine_id))
            with_cap = [(m, h) for m, h in matching
                        if h.has_capacity()]
            if with_cap:
                return with_cap, None
            if (rr.affinity_waited < self.affinity_wait_steps
                    and time.perf_counter() < rr.deadline()):
                return [], matching[0][1]
            # wait budget spent: spill below — the recompute registers
            # the prefix on the spill engine, replicating a hot family
        ranked = sorted(
            ((m, h) for m, h in scored if h.has_capacity()),
            key=lambda mh: (-mh[0], mh[1].load(), mh[1].engine_id))
        return ranked, None

    def _dispatch_pending(self):
        """Drain the pending queue highest-priority-first onto ranked
        engines; requests no engine can hold (or that are holding for
        a full affinity target) stay pending.  Preemption (when
        enabled) triggers for a request that outranks a running one
        once every engine it may use is full."""
        if not self.pending:
            return
        queue, self.pending = self.pending, []
        queue.sort(key=lambda rr: (-rr.priority, rr.deadline(), rr.rid))
        leftover: List[RouterRequest] = []
        for rr in queue:
            placed = False
            hold = None
            while not placed:
                # re-rank after a geometry rejection: the rejected
                # engine just left the candidate set, which can turn a
                # match-only ranking into a least-loaded fallback with
                # FREE capacity — preemption must stay the last resort
                n_rej = len(rr.rejected_engines)
                ranked, hold = self._rank_engines(rr)
                for match, h in ranked:
                    if self._dispatch(rr, h, match):
                        placed = True
                        break
                if placed or len(rr.rejected_engines) == n_rej:
                    break        # no new rejections: re-ranking is moot
            if not placed and self.preempt:
                placed = self._preempt_and_place(rr, only=hold)
            if not placed:
                if hold is not None:
                    rr.affinity_waited += 1
                    self.tracer.event(
                        rr.rid, "affinity_hold",
                        engine=hold.engine_id,
                        hold_round=rr.affinity_waited)
                leftover.append(rr)
        # preemption victims appended themselves to self.pending
        self.pending = leftover + self.pending

    def _dispatch(self, rr: RouterRequest, h: EngineHandle,
                  match: int) -> bool:
        """Hand one request to one engine.  A ValueError from
        ``add_request`` means THIS engine cannot hold the request
        (heterogeneous pools: too few pages, narrow block table) — the
        caller tries the next candidate.

        A request carrying extracted KV pages (``rr.kv_buffer``) tries
        ``inject_request`` FIRST — migrated resume, zero re-prefill;
        an engine that cannot take the buffer (geometry/kv_dtype
        mismatch, no free slot) falls back to ``add_request`` on the
        same engine (re-prefill resume, the r15 path).  Either way a
        successful dispatch consumes the buffer — the request's tokens
        outgrow its coverage from here on."""
        injected = False
        erid = None
        if rr.kv_buffer is not None:
            inject = getattr(h.engine, "inject_request", None)
            if inject is not None:
                try:
                    erid = inject(rr.resume_prompt(), rr.kv_buffer,
                                  max_new_tokens=rr.remaining_budget(),
                                  eos_token_id=rr.eos_token_id)
                    injected = True
                except EngineRPCError:
                    # dead remote engine: don't burn a second retry
                    # cycle on the add_request fallback
                    self._lose_engine(h)
                    return False
                except (ValueError, RuntimeError):
                    erid = None     # fall through to re-prefill resume
        if not injected:
            try:
                erid = h.engine.add_request(
                    rr.resume_prompt(),
                    max_new_tokens=rr.remaining_budget(),
                    eos_token_id=rr.eos_token_id)
            except EngineRPCError:
                # a remote engine whose RPCs exhausted their retries is
                # LOST, not "too small" — drain it (requeues anything
                # in flight there) and try the next candidate; rr is
                # not in _inflight yet so it stays pending either way
                self._lose_engine(h)
                return False
            except ValueError:
                rr.rejected_engines.add(h.engine_id)
                return False
        rr.kv_buffer = None
        rr.state = "dispatched"
        rr.engine_id = h.engine_id
        rr.engine_req_id = erid
        if injected:
            # inject_request lands straight on a slot, not the waiting
            # queue — find the live request object there
            rr.engine_req = next(
                (r for r in getattr(h.engine, "slots", [])
                 if r is not None and r.req_id == erid), None)
        else:
            # add_request APPENDS to the engine's waiting queue — grab
            # the live request object for host-side sync (first-token
            # marks, drain fallback)
            rr.engine_req = (h.engine.waiting[-1]
                             if h.engine.waiting else None)
        rr.routed_by_prefix = match > 0
        now = time.perf_counter()
        rr.hops.append([h.engine_id, erid, now, None])
        if self.tracer.enabled:
            # ONE record: a "dispatch" SPAN covering the pending wait
            # (submit..dispatch / requeue..re-dispatch — the tile the
            # chain validator checks) whose args carry the route
            # decision and its affinity outcome
            outcome = ("prefix" if match > 0 else
                       "random" if self.route_policy == "random" else
                       "spilled" if rr.affinity_waited else
                       "least_loaded")
            self.tracer.span(rr.rid, "dispatch", rr.t_requeued, now,
                             engine=h.engine_id, match_tokens=match,
                             route=outcome, requeues=rr.requeues,
                             migrated=injected)
        if match > 0:
            self._m_prefix_hits.inc()
        role = getattr(h.engine, "role", "mixed")
        self._role_children.get(role, self._role_children["mixed"]).inc()
        bs = getattr(h.engine, "block_size", 0)
        if bs and getattr(h.engine, "prefix_cache", None) is not None:
            h.note_routed(None, keys=rr.routing_keys_for(bs))
        h.note_dispatched()
        self._inflight[(h.engine_id, erid)] = rr
        return True

    # ---- completion -----------------------------------------------------
    def _sync_first_tokens(self, h: EngineHandle):
        """TTFT marks for requests whose first token just landed on
        this engine (pure host-side reads of the live request object)."""
        for key, rr in self._inflight.items():
            if key[0] != h.engine_id or rr.t_first_token:
                continue
            if rr.base_output:
                # a requeued request's first token predates this engine
                continue
            ereq = rr.engine_req
            if ereq is not None and ereq.output_ids:
                rr.t_first_token = (ereq.t_first_token
                                    or time.perf_counter())
                self.tracer.event(rr.rid, "first_token",
                                  ts=rr.t_first_token,
                                  ttft=rr.t_first_token - rr.t_submit)

    def _complete(self, rr: RouterRequest, ereq) -> None:
        rr.output_ids = rr.base_output + (list(ereq.output_ids)
                                          if ereq is not None else [])
        rr.truncated = bool(getattr(ereq, "truncated", False))
        rr.state = "done"
        rr.t_done = time.perf_counter()
        if not rr.t_first_token:
            rr.t_first_token = (getattr(ereq, "t_first_token", 0.0)
                                or rr.t_done)
            self.tracer.event(rr.rid, "first_token",
                              ts=rr.t_first_token,
                              ttft=rr.t_first_token - rr.t_submit)
        if rr.hops and rr.hops[-1][3] is None:
            # close the final engine segment (a request finishing
            # through the requeue path closed it there already)
            rr.hops[-1][3] = rr.t_done
            self.tracer.span(rr.rid, "on_engine", rr.hops[-1][2],
                             rr.t_done, engine=rr.hops[-1][0])
        rr.engine_req = None
        rr.kv_buffer = None     # finished records must not pin page KV
        self._account_slo(rr)
        self.finished[rr.rid] = rr
        while len(self.finished) > self.max_finished:
            self.finished.popitem(last=False)
        self._done_backlog.append(rr.rid)
        outcome = "truncated" if rr.truncated else "completed"
        self._m_requests.labels(outcome=outcome).inc()
        self.tracer.event(
            rr.rid, "finish", ts=rr.t_done, outcome=outcome,
            tokens=len(rr.output_ids), requeues=rr.requeues,
            ttft_outcome=rr.summary["slo"]["ttft"],
            tpot_outcome=rr.summary["slo"]["tpot"])

    def _account_slo(self, rr: RouterRequest) -> None:
        """Judge the finished request's MEASURED latencies against its
        declared targets, feed the reservoirs/quantile gauges, and
        attach the per-request summary to the record.  Every completion
        contributes exactly one outcome per kind, so for each kind the
        attainment counters sum to completed admissions (the bench's
        arithmetic gate)."""
        n = len(rr.output_ids)
        ttft = rr.t_first_token - rr.t_submit
        if ttft < 0 or not n:
            ttft = None                      # nothing ever streamed
        mean_tpot = ((rr.t_done - rr.t_first_token) / (n - 1)
                     if n > 1 and rr.t_first_token else None)
        if ttft is None or rr.ttft_target is None:
            ttft_out = "no_target" if rr.ttft_target is None else "missed"
        else:
            ttft_out = ("attained" if ttft <= rr.ttft_target
                        else "missed")
        if rr.tpot_target is None or mean_tpot is None:
            # an unmeasurable TPOT (0/1-token output) has no per-token
            # stream to judge — it counts as untargeted, keeping the
            # per-kind sum equal to completions
            tpot_out = "no_target"
        else:
            tpot_out = ("attained" if mean_tpot <= rr.tpot_target
                        else "missed")
        self._slo_counts[("ttft", ttft_out)] += 1
        self._slo_counts[("tpot", tpot_out)] += 1
        self._slo_children[("ttft", ttft_out)].inc()
        self._slo_children[("tpot", tpot_out)].inc()
        if ttft is not None:
            self._ttft_res.add(ttft)
        if mean_tpot is not None:
            self._tpot_res.add(mean_tpot)
        # quantile gauges are published every 16th completion (and on
        # every slo_snapshot/health_payload read, which recomputes
        # exactly): completions stay O(1) reservoir adds instead of
        # six sorted-window passes each
        self._completions += 1
        if self._completions % 16 == 1:
            self._publish_latency_gauges()
        rr.summary = {
            "tokens": n,
            "ttft": ttft,
            "mean_tpot": mean_tpot,
            "requeues": rr.requeues,
            "migrations": rr.migrations,
            "engines_visited": rr.engines_visited(),
            "outcome": "truncated" if rr.truncated else "completed",
            "ttft_target": rr.ttft_target,
            "tpot_target": rr.tpot_target,
            "slo": {"ttft": ttft_out, "tpot": tpot_out},
        }
