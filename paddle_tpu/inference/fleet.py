"""Multi-process serving fleet — the cross-process engine data plane
(round 23, ROADMAP item 4).

The reference Paddle ran its fleet executor over a brpc message bus;
the jax_graft equivalent is deliberately smaller: one engine-server
process wraps one :class:`ContinuousBatchingEngine` and exposes the
full engine API over a length-prefixed socket protocol, and a
:class:`RemoteEngineClient` presents the in-process engine interface so
:class:`ServingRouter` drives N processes through the SAME
dispatch/drain/requeue/migrate state machine it runs in-process —
same routing keys, same SLO plane, same capacity signals.

Wire protocol (one frame per message, either direction)::

    header   <4sII   magic b"PTF1", json_len, n_blobs
    lengths  n_blobs x <Q   byte length of each raw blob
    payload  json_len bytes of JSON (the message object)
    blobs    concatenated raw bytes (KVPageBuffer planes)

Requests are ``{"v":1, "tok": <client token>, "id": <monotonic int>,
"method": ..., "params": {...}}``; responses ``{"id":..., "ok":true,
"result":...}`` or ``{"id":..., "ok":false, "error":{"type","msg"}}``
with the error type mapped back onto the in-process exception contract
(KeyError / ValueError / RuntimeError) client-side — the router's
existing error handling keeps working verbatim across the wire.

``KVPageBuffer`` crosses the wire verbatim: its self-describing header
rides in the JSON, its ``codes`` (and int8 ``scales``) host arrays ride
as raw blobs — ONE payload per dtype plane, zero re-encoding.  The
server validates blob sizes against the declared geometry BEFORE any
engine call, and ``inject_request`` keeps r19's pre-side-effect error
contract (ValueError = never fits, RuntimeError = transient).

Robustness contract:

* every socket operation is deadline-bounded (``settimeout`` derived
  from the per-method RPC deadline — no unbounded blocking call);
* transient failures (connection loss, timeouts, torn frames) retry
  with capped exponential backoff + jitter (:class:`RetryPolicy`,
  shared with ``EngineHandle``'s /healthz scrape);
* retries are SAFE: every request carries a (client token, rpc id)
  pair and the server replays the cached response for a duplicate —
  a resent ``step`` never double-advances the engine;
* retries exhausted raise :class:`EngineRPCError`, which the router's
  step/probe machinery turns into drain-and-requeue from the router's
  OWN record (reason="engine_lost", zero drops — the r15 contract,
  surviving ``kill -9`` of a real process);
* the network itself is fault-injectable: ``rpc.send`` / ``rpc.recv``
  / ``rpc.accept`` sites (testing/faults.py) fire on both sides of the
  wire, per process.

Threading: :class:`EngineServer` serializes all engine access under
``_engine_lock`` (one handler thread per connection) and guards its
RPC-dedup/tracking maps under ``_lock`` (strict order: engine lock
outer).  :class:`RemoteEngineClient` is single-threaded by design —
it is owned by one router loop, exactly like an in-process engine.
"""
from __future__ import annotations

import json
import os
import random
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _metrics
from ..ops.paged_attention import KVPageBuffer
from ..testing.faults import FaultDrop, fault_point

__all__ = [
    "RetryPolicy", "EngineRPCError", "ProtocolError",
    "send_frame", "recv_frame", "buffer_to_wire", "buffer_from_wire",
    "RemoteEngineClient", "RemoteRequestView", "EngineServer",
    "EngineProcess", "RPC_METHODS",
]

_MAGIC = b"PTF1"
_HEADER = struct.Struct("<4sII")     # magic, json_len, n_blobs
_BLOBLEN = struct.Struct("<Q")
_MAX_JSON = 64 << 20
_MAX_BLOBS = 8
_MAX_BLOB = 16 << 30

#: the closed RPC method set — also the graftlint label domain for
#: ``router_rpc_*{method=...}``
RPC_METHODS = ("hello", "add_request", "step", "preempt_request",
               "extract_request", "inject_request", "health_payload",
               "ping", "shutdown")

# RPC latency is network + engine step time — the default buckets top
# out too low for a CPU-compile step, so extend the tail
_RPC_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)


class ProtocolError(OSError):
    """A torn / corrupt / mismatched frame.  An :class:`OSError` so the
    client's transient-retry machinery treats it like any other broken
    connection: drop the socket, reconnect, resend (dedup-safe)."""


class EngineRPCError(RuntimeError):
    """An RPC that exhausted its retries (or hit a non-engine server
    failure).  Deliberately NOT a ValueError: the router maps it to the
    engine-lost drain path, never to a capacity rejection."""

    def __init__(self, msg: str, method: str = "", attempts: int = 0):
        super().__init__(msg)
        self.method = method
        self.attempts = attempts


# exception types the server serializes by name and the client
# re-raises as the in-process engine contract
_ERROR_TYPES = {"KeyError": KeyError, "ValueError": ValueError,
                "RuntimeError": RuntimeError, "TypeError": TypeError}


# ---------------------------------------------------------------------------
# retry policy (shared with EngineHandle /healthz scraping)
# ---------------------------------------------------------------------------
class RetryPolicy:
    """Capped exponential backoff with jitter.

    ``delay(attempt)`` for the 1-based ``attempt``-th failure is
    ``min(max_delay, base_delay * 2**(attempt-1)) * (1 + jitter*u)``
    with ``u`` uniform in [0, 1).  ``clock``/``sleep``/``rng`` are
    injectable so tests pin the arithmetic on a stub clock."""

    def __init__(self, max_attempts: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.5,
                 rng=None, sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()
        self.sleep = sleep

    def delay(self, attempt: int) -> float:
        base = min(self.max_delay,
                   self.base_delay * (2.0 ** (max(1, attempt) - 1)))
        return base * (1.0 + self.jitter * self._rng.random())

    def run(self, fn, retry_on=(OSError,), on_retry=None):
        """Call ``fn`` with up to ``max_attempts`` tries; sleeps
        ``delay(i)`` between them.  The final failure re-raises."""
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn()
            except retry_on as e:
                if attempt >= self.max_attempts:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep(self.delay(attempt))


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------
def _remaining(deadline: Optional[float]) -> Optional[float]:
    if deadline is None:
        return None
    rem = deadline - time.monotonic()
    if rem <= 0:
        raise TimeoutError("rpc deadline exhausted")
    return rem


def send_frame(sock: socket.socket, obj: dict,
               blobs: Sequence[bytes] = (), deadline: float = None):
    """Write one frame (header + blob lengths + JSON + blobs), every
    ``sendall`` bounded by ``deadline``."""
    payload = json.dumps(obj, separators=(",", ":"),
                         default=str).encode("utf-8")
    head = [_HEADER.pack(_MAGIC, len(payload), len(blobs))]
    head.extend(_BLOBLEN.pack(len(b)) for b in blobs)
    head.append(payload)
    sock.settimeout(_remaining(deadline))
    sock.sendall(b"".join(head))
    for b in blobs:
        sock.settimeout(_remaining(deadline))
        sock.sendall(b)


def _recv_exact(sock: socket.socket, n: int,
                deadline: Optional[float]) -> bytes:
    chunks, got = [], 0
    while got < n:
        sock.settimeout(_remaining(deadline))
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise ConnectionResetError("peer closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket,
               deadline: float = None) -> Tuple[dict, List[bytes]]:
    """Read one frame; raises :class:`ProtocolError` on a corrupt
    header/JSON, ``TimeoutError`` past ``deadline``."""
    head = _recv_exact(sock, _HEADER.size, deadline)
    try:
        magic, json_len, n_blobs = _HEADER.unpack(head)
    except struct.error as e:             # pragma: no cover - fixed size
        raise ProtocolError(f"bad frame header: {e}") from e
    if magic != _MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if json_len > _MAX_JSON or n_blobs > _MAX_BLOBS:
        raise ProtocolError(
            f"frame exceeds limits (json={json_len}, blobs={n_blobs})")
    lens = []
    for _ in range(n_blobs):
        (blen,) = _BLOBLEN.unpack(_recv_exact(sock, _BLOBLEN.size,
                                              deadline))
        if blen > _MAX_BLOB:
            raise ProtocolError(f"blob of {blen} bytes exceeds limit")
        lens.append(blen)
    payload = _recv_exact(sock, json_len, deadline)
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"bad frame payload: {e}") from e
    if not isinstance(obj, dict):
        raise ProtocolError("frame payload is not an object")
    blobs = [_recv_exact(sock, blen, deadline) for blen in lens]
    return obj, blobs


# ---------------------------------------------------------------------------
# KVPageBuffer <-> wire
# ---------------------------------------------------------------------------
def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes                   # jax dependency, always baked
        return np.dtype(getattr(ml_dtypes, name))


def buffer_to_wire(buf: Optional[KVPageBuffer]):
    """``(header_dict | None, [codes_bytes, scales_bytes?])`` — the
    header pins the geometry, the blobs are the raw host planes (one
    per dtype), byte-exact."""
    if buf is None:
        return None, []
    header = {"n_pages": int(buf.n_pages), "n_tokens": int(buf.n_tokens),
              "block_size": int(buf.block_size),
              "num_kv_heads": int(buf.num_kv_heads),
              "head_dim": int(buf.head_dim),
              "num_layers": int(buf.num_layers),
              "kv_dtype": str(buf.kv_dtype),
              "codes_dtype": str(np.asarray(buf.codes).dtype),
              "has_scales": buf.scales is not None}
    blobs = [np.ascontiguousarray(buf.codes).tobytes()]
    if buf.scales is not None:
        blobs.append(np.ascontiguousarray(
            np.asarray(buf.scales, np.float32)).tobytes())
    return header, blobs


def buffer_from_wire(header: Optional[dict],
                     blobs: Sequence[bytes]) -> Optional[KVPageBuffer]:
    """Rebuild a :class:`KVPageBuffer` from its wire form, validating
    every blob length against the declared geometry BEFORE constructing
    anything — a mismatched frame raises ValueError with no side
    effect (r19's pre-side-effect contract holds across the wire)."""
    if header is None:
        return None
    try:
        L = int(header["num_layers"])
        n_pages = int(header["n_pages"])
        bs = int(header["block_size"])
        hkv = int(header["num_kv_heads"])
        d = int(header["head_dim"])
        dtype = _np_dtype(str(header["codes_dtype"]))
    except (KeyError, TypeError, ValueError, AttributeError) as e:
        raise ValueError(f"malformed KVPageBuffer header: {e}") from e
    shape = (2 * L, n_pages, bs, hkv, d)
    want = int(np.prod(shape)) * dtype.itemsize
    if not blobs or len(blobs[0]) != want:
        raise ValueError(
            "KVPageBuffer codes blob is %d bytes, geometry %r wants %d"
            % (len(blobs[0]) if blobs else 0, shape, want))
    codes = np.frombuffer(blobs[0], dtype).reshape(shape)
    scales = None
    if header.get("has_scales"):
        sshape = (2 * L, n_pages, hkv)
        swant = int(np.prod(sshape)) * 4
        if len(blobs) < 2 or len(blobs[1]) != swant:
            raise ValueError(
                "KVPageBuffer scales blob is %d bytes, geometry %r "
                "wants %d" % (len(blobs[1]) if len(blobs) > 1 else 0,
                              sshape, swant))
        scales = np.frombuffer(blobs[1], np.float32).reshape(sshape)
    return KVPageBuffer(
        codes=codes, scales=scales, n_pages=n_pages,
        n_tokens=int(header["n_tokens"]), block_size=bs,
        num_kv_heads=hkv, head_dim=d, num_layers=L,
        kv_dtype=str(header["kv_dtype"]))


def _fleet_metrics(registry=None):
    r = registry if registry is not None else _metrics.default_registry()
    return (
        r.counter(
            "router_rpc_requests_total",
            "logical fleet RPCs by method and outcome (ok / error) — "
            "one count per call, however many attempts it took",
            labels=("method", "outcome")),
        r.counter(
            "router_rpc_retries_total",
            "transient-failure retries (reconnect + resend) by method; "
            "a healthy fleet holds this near zero",
            labels=("method",)),
        r.histogram(
            "router_rpc_latency_seconds",
            "wall time of logical fleet RPCs (first attempt through "
            "final outcome, retries included)",
            labels=("method",), buckets=_RPC_BUCKETS),
        r.counter(
            "fleet_engine_process_restarts_total",
            "engine-server subprocess restarts through "
            "EngineProcess.restart()"),
    )


# ---------------------------------------------------------------------------
# client-side request views
# ---------------------------------------------------------------------------
@dataclass
class RemoteRequestView:
    """The client-side twin of the engine's live request object — the
    router reads these exactly as it reads ``GenerationRequest`` (slot,
    state, output_ids, t_first_token, truncated), synced from ``step``
    responses.  ``t_first_token`` is stamped CLIENT-side when the first
    output token is observed: ``perf_counter`` is not comparable across
    processes, and the router's TTFT math runs on ITS clock."""
    req_id: int
    prompt_ids: Optional[np.ndarray] = None
    output_ids: List[int] = field(default_factory=list)
    slot: int = -1
    state: str = "waiting"
    t_first_token: float = 0.0
    truncated: bool = False
    max_new_tokens: int = 0


class _RemotePrefixTable:
    """Membership view of the server engine's prefix-cache table
    (blake2b page keys), synced from step responses — the router's
    affinity routing only ever asks ``key in pc.table``."""

    def __init__(self):
        self.table: Dict[bytes, int] = {}

    def replace(self, hex_keys: Sequence[str]):
        self.table = {bytes.fromhex(k): 0 for k in hex_keys}


# ---------------------------------------------------------------------------
# the client
# ---------------------------------------------------------------------------
class RemoteEngineClient:
    """Drives one engine-server process through the wire protocol while
    presenting the in-process :class:`ContinuousBatchingEngine`
    interface the router already speaks (``add_request`` / ``step`` /
    ``has_work`` / ``finished`` / ``preempt_request`` /
    ``extract_request`` / ``inject_request`` / ``health_payload`` /
    ``waiting`` / ``slots`` / ``prefix_cache`` / ``block_size`` ...).

    Single-threaded by design (one owner: the router loop).  Every RPC
    is deadline-bounded and retried per :class:`RetryPolicy`; the
    (token, id) dedup pair makes retries side-effect-safe.

    ``begin_step()`` / ``finish_step()`` split the step RPC so a router
    can FAN OUT one ``step`` to every remote engine and then collect —
    N processes genuinely step concurrently, which is the point of
    leaving the process."""

    DEFAULT_TIMEOUTS = {
        "hello": 60.0, "add_request": 60.0, "step": 180.0,
        "preempt_request": 60.0, "extract_request": 180.0,
        "inject_request": 180.0, "health_payload": 5.0,
        "ping": 5.0, "shutdown": 5.0,
    }

    def __init__(self, address, retry: Optional[RetryPolicy] = None,
                 timeouts: Optional[Dict[str, float]] = None,
                 eager: bool = True, registry=None,
                 health_cache_s: float = 0.25):
        if isinstance(address, str):
            host, _, port = address.rpartition(":")
            address = (host or "127.0.0.1", int(port))
        self._address = (address[0], int(address[1]))
        self.retry = retry if retry is not None else RetryPolicy()
        self._timeouts = dict(self.DEFAULT_TIMEOUTS)
        if timeouts:
            self._timeouts.update(timeouts)
        self._health_cache_s = float(health_cache_s)
        self._sock: Optional[socket.socket] = None
        self._token = os.urandom(8).hex()
        self._next_id = 1
        self._step_pending: Optional[dict] = None
        self._views: "OrderedDict[int, RemoteRequestView]" = OrderedDict()
        self.finished: Dict[int, RemoteRequestView] = {}
        self._hello: Optional[dict] = None
        self._prefix = _RemotePrefixTable()
        self._health: Optional[Tuple[float, dict]] = None
        (self._m_requests, self._m_retries, self._m_latency,
         _restarts) = _fleet_metrics(registry)
        if eager:
            self._ensure_hello()

    # ---- static engine surface (from the hello handshake) ---------------
    def _ensure_hello(self) -> dict:
        if self._hello is None:
            self._hello, _ = self._call("hello", {})
        return self._hello

    @property
    def engine_id(self):
        return self._ensure_hello().get("engine_id")

    @property
    def role(self) -> str:
        return self._ensure_hello().get("role", "mixed")

    @property
    def block_size(self) -> int:
        return int(self._ensure_hello().get("block_size", 0))

    @property
    def prefix_cache(self):
        if not self._ensure_hello().get("has_prefix_cache"):
            return None
        return self._prefix

    def migration_geometry(self):
        geo = self._ensure_hello().get("migration_geometry")
        return tuple(geo) if geo is not None else None

    @property
    def server_pid(self) -> Optional[int]:
        return self._ensure_hello().get("pid")

    # ---- live request surface -------------------------------------------
    @property
    def waiting(self) -> List[RemoteRequestView]:
        return [v for v in self._views.values() if v.slot < 0]

    @property
    def slots(self) -> List[RemoteRequestView]:
        return [v for v in self._views.values() if v.slot >= 0]

    def has_work(self) -> bool:
        return bool(self._views)

    # ---- engine API over the wire ---------------------------------------
    def add_request(self, prompt_ids, max_new_tokens: int = 16,
                    eos_token_id: Optional[int] = None,
                    temperature: float = 0.0, top_k: int = 0,
                    top_p: float = 0.0, seed: int = 0) -> int:
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        res, _ = self._call("add_request", {
            "prompt_ids": prompt.tolist(),
            "max_new_tokens": int(max_new_tokens),
            "eos_token_id": (int(eos_token_id)
                             if eos_token_id is not None else None),
            "temperature": float(temperature), "top_k": int(top_k),
            "top_p": float(top_p), "seed": int(seed)})
        erid = int(res["req_id"])
        self._views[erid] = RemoteRequestView(
            req_id=erid, prompt_ids=prompt,
            max_new_tokens=int(max_new_tokens))
        return erid

    def inject_request(self, prompt_ids, buffer: KVPageBuffer,
                       max_new_tokens: int = 16,
                       eos_token_id: Optional[int] = None,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 0.0, seed: int = 0) -> int:
        prompt = np.asarray(prompt_ids, np.int64).reshape(-1)
        header, blobs = buffer_to_wire(buffer)
        res, _ = self._call("inject_request", {
            "prompt_ids": prompt.tolist(), "buffer": header,
            "max_new_tokens": int(max_new_tokens),
            "eos_token_id": (int(eos_token_id)
                             if eos_token_id is not None else None),
            "temperature": float(temperature), "top_k": int(top_k),
            "top_p": float(top_p), "seed": int(seed)}, blobs=blobs)
        erid = int(res["req_id"])
        self._views[erid] = RemoteRequestView(
            req_id=erid, prompt_ids=prompt, slot=int(res.get("slot", 0)),
            state=str(res.get("state", "running")),
            max_new_tokens=int(max_new_tokens))
        return erid

    def preempt_request(self, req_id: int):
        res, _ = self._call("preempt_request", {"req_id": int(req_id)})
        self._views.pop(int(req_id), None)
        return (np.asarray(res["prompt_ids"], np.int64),
                list(res["generated"]))

    def extract_request(self, req_id: int):
        res, rblobs = self._call("extract_request",
                                 {"req_id": int(req_id)})
        self._views.pop(int(req_id), None)
        buf = buffer_from_wire(res.get("buffer"), rblobs)
        return (np.asarray(res["prompt_ids"], np.int64),
                list(res["generated"]), buf)

    def health_payload(self) -> dict:
        if self._health is not None:
            age = time.monotonic() - self._health[0]
            if 0 <= age < self._health_cache_s:
                return self._health[1]
        res, _ = self._call("health_payload", {})
        self._health = (time.monotonic(), res)
        return res

    def ping(self) -> bool:
        try:
            self._call("ping", {})
            return True
        except EngineRPCError:
            return False

    def shutdown_server(self):
        """Ask the server process to exit cleanly (it replies first)."""
        try:
            self._call("shutdown", {})
        finally:
            self.close()

    # ---- the step fan-out -----------------------------------------------
    def begin_step(self):
        """Fire the step RPC without waiting for the reply (pure
        opportunistic send — a send failure is absorbed and
        ``finish_step`` retries from scratch)."""
        if self._step_pending is not None:
            return
        self._ensure_hello()
        rid = self._next_id
        self._next_id += 1
        msg = {"v": 1, "tok": self._token, "id": rid, "method": "step",
               "params": {}}
        pend = {"rid": rid, "msg": msg, "t0": time.perf_counter(),
                "sent": False}
        self._step_pending = pend
        try:
            deadline = time.monotonic() + self._timeouts["step"]
            sock = self._connect(deadline)
            self._send(sock, msg, (), deadline)
            pend["sent"] = True
        except OSError:
            self._drop_conn()

    def finish_step(self) -> List[int]:
        """Collect (or run) the step RPC and fold the response into the
        local views/finished record.  Returns the done erid list."""
        pend = self._step_pending
        if pend is None:
            self.begin_step()
            pend = self._step_pending
        msg, t0 = pend["msg"], pend["t0"]
        last: Optional[BaseException] = None
        try:
            for attempt in range(1, self.retry.max_attempts + 1):
                deadline = time.monotonic() + self._timeouts["step"]
                try:
                    sock = self._connect(deadline)
                    if not (attempt == 1 and pend["sent"]):
                        self._send(sock, msg, (), deadline)
                    resp, rblobs = self._recv_for(sock, pend["rid"],
                                                  deadline)
                    result = self._unwrap("step", t0, resp)
                    return self._apply_step(result)
                except OSError as e:
                    last = e
                    self._drop_conn()
                    if attempt >= self.retry.max_attempts:
                        break
                    self._m_retries.labels(method="step").inc()
                    self.retry.sleep(self.retry.delay(attempt))
        finally:
            self._step_pending = None
        self._observe("step", "error", t0)
        raise EngineRPCError(
            "step rpc to %s:%d failed after %d attempts: %r"
            % (self._address[0], self._address[1],
               self.retry.max_attempts, last),
            method="step", attempts=self.retry.max_attempts) from last

    def step(self) -> List[int]:
        self.begin_step()
        return self.finish_step()

    # ---- plumbing --------------------------------------------------------
    def close(self):
        self._drop_conn()

    def _drop_conn(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:       # pragma: no cover - close never blocks
                pass
            self._sock = None

    def _connect(self, deadline: float) -> socket.socket:
        if self._sock is not None:
            return self._sock
        s = socket.create_connection(
            self._address, timeout=max(0.05, _remaining(deadline) or 5.0))
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s
        return s

    def _send(self, sock, msg, blobs, deadline):
        try:
            fault_point("rpc.send")
        except FaultDrop:
            return        # the bytes vanished; the reply deadline catches it
        send_frame(sock, msg, blobs, deadline)

    def _recv_for(self, sock, rid: int, deadline: float):
        while True:
            try:
                fault_point("rpc.recv")
            except FaultDrop:
                raise TimeoutError("fault-injected drop on rpc.recv") \
                    from None
            resp, rblobs = recv_frame(sock, deadline)
            got = resp.get("id")
            if got == rid:
                return resp, rblobs
            if isinstance(got, int) and got < rid:
                continue          # stale reply from an abandoned attempt
            raise ProtocolError(f"response id {got!r}, expected {rid}")

    def _settle_pending(self):
        """A non-step RPC while a step reply is in flight: drain the
        reply (short grace) so the socket is clean, else drop the
        connection — the dedup cache protects the resend either way."""
        pend = self._step_pending
        if pend is None:
            return
        try:
            sock = self._connect(time.monotonic() + 1.0)
            resp, _ = self._recv_for(sock, pend["rid"],
                                     time.monotonic() + 1.0)
            result = self._unwrap("step", pend["t0"], resp)
            self._apply_step(result)
        except (OSError, EngineRPCError, KeyError, ValueError,
                RuntimeError):
            self._drop_conn()
        finally:
            self._step_pending = None

    def _call(self, method: str, params: dict,
              blobs: Sequence[bytes] = ()):
        self._settle_pending()
        rid = self._next_id
        self._next_id += 1
        msg = {"v": 1, "tok": self._token, "id": rid, "method": method,
               "params": params}
        t0 = time.perf_counter()
        timeout = self._timeouts.get(method, 30.0)
        last: Optional[BaseException] = None
        for attempt in range(1, self.retry.max_attempts + 1):
            deadline = time.monotonic() + timeout
            try:
                sock = self._connect(deadline)
                self._send(sock, msg, blobs, deadline)
                resp, rblobs = self._recv_for(sock, rid, deadline)
                return self._unwrap(method, t0, resp), rblobs
            except OSError as e:
                last = e
                self._drop_conn()
                if attempt >= self.retry.max_attempts:
                    break
                self._m_retries.labels(method=method).inc()
                self.retry.sleep(self.retry.delay(attempt))
        self._observe(method, "error", t0)
        raise EngineRPCError(
            "%s rpc to %s:%d failed after %d attempts: %r"
            % (method, self._address[0], self._address[1],
               self.retry.max_attempts, last),
            method=method, attempts=self.retry.max_attempts) from last

    def _unwrap(self, method: str, t0: float, resp: dict):
        if not resp.get("ok", False):
            err = resp.get("error") or {}
            self._observe(method, "error", t0)
            cls = _ERROR_TYPES.get(err.get("type"), EngineRPCError)
            raise cls(err.get("msg", "remote engine error"))
        self._observe(method, "ok", t0)
        return resp.get("result")

    def _observe(self, method: str, outcome: str, t0: float):
        self._m_requests.labels(method=method, outcome=outcome).inc()
        self._m_latency.labels(method=method).observe(
            time.perf_counter() - t0)

    def _apply_step(self, result: dict) -> List[int]:
        now = time.perf_counter()
        done = [int(x) for x in (result.get("done") or [])]
        for erid_s, rec in (result.get("finished") or {}).items():
            erid = int(erid_s)
            v = self._views.pop(erid, None)
            t_ft = v.t_first_token if (v and v.t_first_token) else now
            self.finished[erid] = RemoteRequestView(
                req_id=erid,
                prompt_ids=v.prompt_ids if v is not None else None,
                output_ids=[int(t) for t in rec.get("output_ids", [])],
                slot=-1, state="done", t_first_token=t_ft,
                truncated=bool(rec.get("truncated", False)))
        for st in result.get("status") or []:
            erid = int(st["id"])
            if st.get("state") == "gone":
                self._views.pop(erid, None)
                continue
            v = self._views.get(erid)
            if v is None:
                continue
            v.slot = int(st.get("slot", v.slot))
            v.state = str(st.get("state", v.state))
            new = st.get("new") or []
            if new:
                if not v.output_ids and not v.t_first_token:
                    v.t_first_token = now
                v.output_ids.extend(int(t) for t in new)
            v.truncated = bool(st.get("truncated", v.truncated))
        if result.get("prefix_keys") is not None:
            self._prefix.replace(result["prefix_keys"])
        if result.get("health") is not None:
            self._health = (time.monotonic(), result["health"])
        return done


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------
class EngineServer:
    """Wraps ONE engine behind the wire protocol.  One handler thread
    per connection; all engine access serialized under
    ``_engine_lock``; dedup/tracking maps under ``_lock`` (order:
    engine lock outer, never the reverse).  Every socket operation is
    bounded (listener and idle connections poll with short timeouts so
    ``stop()`` always lands)."""

    DUP_CACHE = 256
    DUP_WAIT_S = 300.0

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 idle_poll_s: float = 0.25, frame_timeout_s: float = 60.0,
                 max_prefix_keys: int = 4096):
        self.engine = engine
        self._host, self._port = host, int(port)
        self._idle_poll_s = float(idle_poll_s)
        self._frame_timeout_s = float(frame_timeout_s)
        self._max_prefix_keys = int(max_prefix_keys)
        self._engine_lock = threading.Lock()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_threads: List[threading.Thread] = []
        self._conns: set = set()
        # (client token, rpc id) -> completed response; replayed for a
        # duplicate so a client retry NEVER double-executes
        self._done_rpcs: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._inflight_rpcs: Dict[tuple, threading.Event] = {}
        # erid -> output tokens already shipped in a step response
        self._shipped: Dict[int, int] = {}

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "EngineServer":
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind((self._host, self._port))
        lst.listen(16)
        lst.settimeout(self._idle_poll_s)
        with self._lock:
            self._listener = lst
        t = threading.Thread(target=self._accept_loop,
                             name="fleet-accept", daemon=True)
        self._accept_thread = t
        t.start()
        return self

    @property
    def address(self) -> Tuple[str, int]:
        return self._listener.getsockname()[:2]

    def stop(self, join: bool = True):
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:     # pragma: no cover - close never blocks
                pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:     # pragma: no cover
                pass
        if join:
            if self._accept_thread is not None:
                self._accept_thread.join(timeout=5.0)
            with self._lock:
                threads = list(self._conn_threads)
            for t in threads:
                t.join(timeout=5.0)

    def serve_forever(self):
        """CLI entrypoint body: start, then block until stop() /
        a shutdown RPC (bounded waits only)."""
        if self._listener is None:
            self.start()
        while not self._stop.wait(timeout=0.5):
            pass
        self.stop()

    # ---- socket loops ----------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _peer = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break             # listener closed: shutting down
            try:
                fault_point("rpc.accept")
            except (FaultDrop, ConnectionError, OSError):
                try:
                    conn.close()
                except OSError:   # pragma: no cover
                    pass
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="fleet-conn", daemon=True)
            with self._lock:
                self._conns.add(conn)
                self._conn_threads.append(t)
            t.start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                try:
                    msg, blobs = self._recv_request(conn)
                except socket.timeout:
                    continue      # idle poll tick: re-check _stop
                except (ConnectionError, OSError, EOFError):
                    break
                if msg is None:
                    continue      # injected drop: pretend never arrived
                resp_obj, resp_blobs = self._handle(msg, blobs)
                try:
                    fault_point("rpc.send")
                    send_frame(conn, resp_obj, resp_blobs,
                               time.monotonic() + self._frame_timeout_s)
                except FaultDrop:
                    continue      # reply vanished; dedup serves the retry
                except (ConnectionError, OSError):
                    break
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:       # pragma: no cover
                pass

    def _recv_request(self, conn: socket.socket):
        """One frame with idle-friendly timing: short poll while no
        bytes have arrived (so stop() lands), a real per-frame deadline
        once a header starts flowing."""
        conn.settimeout(self._idle_poll_s)
        first = conn.recv(1)
        if not first:
            raise ConnectionResetError("client closed")
        deadline = time.monotonic() + self._frame_timeout_s
        try:
            return self._recv_request_body(conn, first, deadline)
        except TimeoutError as e:
            # a timeout MID-frame desyncs the stream: tear the
            # connection down (the idle tick is the recv(1) above)
            raise ProtocolError(f"frame stalled mid-read: {e}") from e

    def _recv_request_body(self, conn, first: bytes, deadline: float):
        head = first + _recv_exact(conn, _HEADER.size - 1, deadline)
        magic, json_len, n_blobs = _HEADER.unpack(head)
        if magic != _MAGIC:
            raise ProtocolError(f"bad magic {magic!r}")
        if json_len > _MAX_JSON or n_blobs > _MAX_BLOBS:
            raise ProtocolError("frame exceeds limits")
        lens = []
        for _ in range(n_blobs):
            (blen,) = _BLOBLEN.unpack(
                _recv_exact(conn, _BLOBLEN.size, deadline))
            if blen > _MAX_BLOB:
                raise ProtocolError("blob exceeds limit")
            lens.append(blen)
        payload = _recv_exact(conn, json_len, deadline)
        blobs = [_recv_exact(conn, blen, deadline) for blen in lens]
        try:
            fault_point("rpc.recv")
        except FaultDrop:
            return None, None     # the request "never arrived"
        try:
            msg = json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ProtocolError(f"bad frame payload: {e}") from e
        if not isinstance(msg, dict):
            raise ProtocolError("frame payload is not an object")
        return msg, blobs

    # ---- dedup + dispatch ------------------------------------------------
    def _handle(self, msg: dict, blobs: List[bytes]):
        rid = msg.get("id")
        key = (msg.get("tok"), rid)
        wait_ev = None
        with self._lock:
            cached = self._done_rpcs.get(key)
            if cached is None:
                ev = self._inflight_rpcs.get(key)
                if ev is None:
                    self._inflight_rpcs[key] = threading.Event()
                else:
                    wait_ev = ev
        if cached is not None:
            return cached
        if wait_ev is not None:
            # the same rpc is executing on another connection (client
            # reconnected mid-call): wait for ITS result, bounded
            wait_ev.wait(timeout=self.DUP_WAIT_S)
            with self._lock:
                cached = self._done_rpcs.get(key)
            if cached is not None:
                return cached
            return ({"id": rid, "ok": False,
                     "error": {"type": "EngineRPCError",
                               "msg": "duplicate rpc still executing"}},
                    [])
        try:
            result, rblobs = self._dispatch_rpc(
                msg.get("method"), msg.get("params") or {}, blobs)
            resp = ({"id": rid, "ok": True, "result": result}, rblobs)
        except Exception as e:                        # noqa: BLE001
            resp = ({"id": rid, "ok": False,
                     "error": {"type": type(e).__name__,
                               "msg": str(e)}}, [])
        with self._lock:
            self._done_rpcs[key] = resp
            while len(self._done_rpcs) > self.DUP_CACHE:
                self._done_rpcs.popitem(last=False)
            ev = self._inflight_rpcs.pop(key, None)
        if ev is not None:
            ev.set()
        return resp

    def _dispatch_rpc(self, method: str, params: dict,
                      blobs: List[bytes]):
        if method == "ping":
            return {}, []
        if method == "shutdown":
            self._stop.set()
            return {}, []
        if method == "hello":
            with self._engine_lock:
                return self._do_hello(), []
        if method == "add_request":
            with self._engine_lock:
                return self._do_add(params), []
        if method == "step":
            with self._engine_lock:
                return self._do_step(), []
        if method == "preempt_request":
            with self._engine_lock:
                return self._do_preempt(params), []
        if method == "extract_request":
            with self._engine_lock:
                return self._do_extract(params)
        if method == "inject_request":
            with self._engine_lock:
                return self._do_inject(params, blobs), []
        if method == "health_payload":
            with self._engine_lock:
                return self.engine.health_payload(), []
        raise ValueError(f"unknown rpc method {method!r}")

    # ---- per-method bodies (engine lock held) ----------------------------
    def _do_hello(self) -> dict:
        eng = self.engine
        geo = None
        mg = getattr(eng, "migration_geometry", None)
        if mg is not None:
            g = mg()
            geo = list(g) if g is not None else None
        return {
            "engine_id": getattr(eng, "engine_id", None),
            "role": getattr(eng, "role", "mixed"),
            "block_size": int(getattr(eng, "block_size", 0) or 0),
            "has_prefix_cache":
                getattr(eng, "prefix_cache", None) is not None,
            "migration_geometry": geo,
            "max_slots": len(getattr(eng, "slots", []) or []),
            "pid": os.getpid(),
        }

    def _sampling_kwargs(self, params: dict) -> dict:
        kw = {}
        for name, cast in (("temperature", float), ("top_k", int),
                           ("top_p", float), ("seed", int)):
            if params.get(name):
                kw[name] = cast(params[name])
        return kw

    def _do_add(self, params: dict) -> dict:
        prompt = np.asarray(params["prompt_ids"], np.int64).reshape(-1)
        eos = params.get("eos_token_id")
        erid = self.engine.add_request(
            prompt, max_new_tokens=int(params.get("max_new_tokens", 16)),
            eos_token_id=int(eos) if eos is not None else None,
            **self._sampling_kwargs(params))
        with self._lock:
            self._shipped[int(erid)] = 0
        return {"req_id": int(erid)}

    def _do_inject(self, params: dict, blobs: List[bytes]) -> dict:
        # decode + geometry-validate the buffer BEFORE touching the
        # engine — a torn frame is a ValueError with zero side effects
        buf = buffer_from_wire(params.get("buffer"), blobs)
        if buf is None:
            raise ValueError("inject_request requires a KV buffer")
        prompt = np.asarray(params["prompt_ids"], np.int64).reshape(-1)
        eos = params.get("eos_token_id")
        erid = self.engine.inject_request(
            prompt, buf,
            max_new_tokens=int(params.get("max_new_tokens", 16)),
            eos_token_id=int(eos) if eos is not None else None,
            **self._sampling_kwargs(params))
        with self._lock:
            self._shipped[int(erid)] = 0
        slot = next((i for i, r in enumerate(
            getattr(self.engine, "slots", []) or [])
            if r is not None and r.req_id == erid), 0)
        return {"req_id": int(erid), "slot": int(slot),
                "state": "running"}

    def _do_preempt(self, params: dict) -> dict:
        erid = int(params["req_id"])
        prompt, gen = self.engine.preempt_request(erid)
        with self._lock:
            self._shipped.pop(erid, None)
        return {"prompt_ids": np.asarray(prompt).tolist(),
                "generated": [int(t) for t in gen]}

    def _do_extract(self, params: dict):
        erid = int(params["req_id"])
        ext = getattr(self.engine, "extract_request", None)
        if ext is None:
            prompt, gen = self.engine.preempt_request(erid)
            buf = None
        else:
            prompt, gen, buf = ext(erid)
        with self._lock:
            self._shipped.pop(erid, None)
        header, bblobs = buffer_to_wire(buf)
        return ({"prompt_ids": np.asarray(prompt).tolist(),
                 "generated": [int(t) for t in gen],
                 "buffer": header}, bblobs)

    def _do_step(self) -> dict:
        eng = self.engine
        done = [int(x) for x in (eng.step() if eng.has_work() else [])]
        finished = {}
        for erid in done:
            rec = eng.finished.pop(erid, None)
            finished[str(erid)] = {
                "output_ids": [int(t) for t in rec.output_ids]
                if rec is not None else [],
                "truncated": bool(getattr(rec, "truncated", False))}
        live = {}
        for r in list(getattr(eng, "waiting", []) or []):
            live[r.req_id] = r
        for r in list(getattr(eng, "slots", []) or []):
            if r is not None:
                live[r.req_id] = r
        with self._lock:
            tracked = dict(self._shipped)
        status = []
        for erid, shipped in tracked.items():
            if str(erid) in finished:
                continue
            r = live.get(erid)
            if r is None:
                rec = eng.finished.pop(erid, None)
                if rec is not None:
                    # a completion a step() return ever missed must
                    # degrade to a late completion, never a stall
                    done.append(int(erid))
                    finished[str(erid)] = {
                        "output_ids": [int(t) for t in rec.output_ids],
                        "truncated": bool(getattr(rec, "truncated",
                                                  False))}
                else:
                    status.append({"id": int(erid), "state": "gone"})
                continue
            out = [int(t) for t in r.output_ids]
            status.append({
                "id": int(erid), "state": getattr(r, "state", "running"),
                "slot": int(getattr(r, "slot", -1)),
                "new": out[shipped:], "n": len(out),
                "truncated": bool(getattr(r, "truncated", False))})
        with self._lock:
            for erid_s in finished:
                self._shipped.pop(int(erid_s), None)
            for st in status:
                if st.get("state") == "gone":
                    self._shipped.pop(st["id"], None)
                elif "n" in st:
                    self._shipped[st["id"]] = st["n"]
        payload = {"done": done, "finished": finished, "status": status,
                   "health": eng.health_payload()}
        pc = getattr(eng, "prefix_cache", None)
        if pc is not None:
            keys = list(pc.table.keys())[-self._max_prefix_keys:]
            payload["prefix_keys"] = [k.hex() for k in keys]
        return payload


# ---------------------------------------------------------------------------
# subprocess management
# ---------------------------------------------------------------------------
class EngineProcess:
    """Spawns / kills / restarts one ``tools/engine_server.py``
    subprocess and resolves its listening address through a port file
    (bounded polling).  ``kill()`` is SIGKILL — the drill the router's
    engine-lost path is tested against."""

    def __init__(self, config: dict, server_script=None, python=None,
                 env: Optional[Dict[str, str]] = None,
                 startup_timeout: float = 120.0, registry=None):
        self.config = dict(config)
        self._script = str(server_script) if server_script else str(
            Path(__file__).resolve().parents[2] / "tools"
            / "engine_server.py")
        self._python = str(python) if python else sys.executable
        self.env = dict(env) if env else {}
        self.startup_timeout = float(startup_timeout)
        self._proc: Optional[subprocess.Popen] = None
        self._address: Optional[Tuple[str, int]] = None
        self._dir: Optional[str] = None
        self._m_restarts = _fleet_metrics(registry)[3]

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self._address

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    @property
    def log_path(self) -> Optional[str]:
        return (os.path.join(self._dir, "server.log")
                if self._dir else None)

    def spawn(self) -> Tuple[str, int]:
        if self.alive:
            return self._address
        self._dir = tempfile.mkdtemp(prefix="ptfleet-")
        cfg_path = os.path.join(self._dir, "config.json")
        port_path = os.path.join(self._dir, "port")
        with open(cfg_path, "w") as f:
            json.dump(self.config, f)
        env = {**os.environ, **self.env}
        log = open(os.path.join(self._dir, "server.log"), "w")
        try:
            self._proc = subprocess.Popen(
                [self._python, self._script, "--config", cfg_path,
                 "--port-file", port_path],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
        deadline = time.monotonic() + self.startup_timeout
        while time.monotonic() < deadline:
            if self._proc.poll() is not None:
                raise EngineRPCError(
                    "engine server exited rc=%s during startup (log: %s)"
                    % (self._proc.returncode, self.log_path))
            try:
                with open(port_path) as f:
                    line = f.read().strip()
                if line:
                    host, _, port = line.rpartition(":")
                    self._address = (host or "127.0.0.1", int(port))
                    return self._address
            except (OSError, ValueError):
                pass
            time.sleep(0.05)
        self.kill()
        raise EngineRPCError(
            "engine server did not publish a port within %.0fs (log: %s)"
            % (self.startup_timeout, self.log_path))

    def kill(self):
        """SIGKILL — no goodbye, the failure drill."""
        if self._proc is not None:
            try:
                self._proc.kill()
                self._proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):  # pragma: no cover
                pass

    def terminate(self, timeout: float = 10.0):
        if self._proc is None:
            return
        try:
            self._proc.terminate()
            self._proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.kill()
        except OSError:             # pragma: no cover - already gone
            pass

    def restart(self) -> Tuple[str, int]:
        self.kill()
        self._proc = None
        self._address = None
        self._m_restarts.inc()
        return self.spawn()
