"""Elastic actuator for the serving fleet (round 25).

Closes the loop ROADMAP item 3 left open: r20's capacity plane emits
flap-free ``scale_up`` / ``scale_down`` / ``rebalance`` recommendations
and r19/r23 made KV pages movable — but nothing ACTED.
:class:`ElasticController` is the actuator: it reads
``ServingRouter.capacity_plan()`` after each router step and turns the
committed recommendation into pool changes, all through the router's
unchanged dispatch/drain state machine:

- **scale_up** — admit a cold engine (popped from the ``standby`` pool
  or built by the ``spawn`` factory: an in-process engine, or an
  ``EngineProcess``-backed :class:`~paddle_tpu.inference.fleet.
  RemoteEngineClient`), then WARM it: hot prefix families are copied
  from the most-saturated peers' host tiers into the newcomer's (first
  touch hits host RAM instead of recomputing), and in-flight decode
  work is shed off the hottest peer extract-first so its pages migrate
  over (the newcomer's empty slots make it the ranked dispatch's
  least-loaded target).
- **scale_down** — pick the least-saturated victim and retire it via
  ``router.remove_engine``: every in-flight request drains off through
  the same extract-first requeue the failure path uses, so each resume
  injects its KV pages with ZERO re-prefill (``fate="migrated"``; an
  engine whose pools can't travel degrades to ``"re_prefilled"``).
  The drained engine parks back in ``standby`` (or is handed to the
  ``retire`` callback — kill the subprocess, return the lease).
- **rebalance** — the generalized ``_migrate_ready`` sweep: the plan's
  ranked ``rebalance_pairs`` name concrete (source, target) engines;
  decoding requests are pulled off each source extract-first and
  requeued, and the ranked dispatch lands them (pages and all) on the
  spare capacity.

The controller acts at most once per planner EVALUATION and then holds
for ``cooldown_steps`` router steps — the planner's hysteresis+dwell
already forbids flapping recommendations, and the cooldown keeps the
actuator from re-acting on the same committed action every step while
its effect is still propagating through the windows.

Construction is the only knob: a router without an ElasticController
attached behaves byte-identically to r24 (defaults parity).
"""
from __future__ import annotations

from typing import Dict, List, Optional

__all__ = ["ElasticController"]


class ElasticController:
    """Drives a :class:`~paddle_tpu.inference.router.ServingRouter`'s
    pool membership off its committed capacity plan.

    Call :meth:`step` after every ``router.step()`` (or let a serving
    loop own the cadence).  ``spawn()`` -> engine is consulted on
    scale_up when ``standby`` is empty; ``retire(engine)`` on
    scale_down (default: park in ``standby`` for the next scale_up —
    the in-process fleet shape).
    """

    def __init__(self, router, spawn=None, standby=None, retire=None,
                 min_engines: int = 1, max_engines: int = 8,
                 cooldown_steps: int = 8, max_moves_per_action: int = 4,
                 warm_pages: int = 32, registry=None):
        if router.capacity is None:
            raise ValueError(
                "ElasticController needs capacity monitoring: construct "
                "the ServingRouter with capacity=True (or a "
                "CapacityConfig / FleetCapacityMonitor)")
        self.router = router
        self.spawn = spawn
        self.standby: List = list(standby) if standby else []
        self.retire = retire
        self.min_engines = max(1, int(min_engines))
        self.max_engines = max(self.min_engines, int(max_engines))
        self.cooldown_steps = max(0, int(cooldown_steps))
        self.max_moves_per_action = max(1, int(max_moves_per_action))
        self.warm_pages = max(0, int(warm_pages))
        self._cooldown = 0
        self._acted_evaluations = -1
        # action log for tests/benches: (router evaluation count,
        # action, detail dict)
        self.actions: List[tuple] = []

        from ..observability import default_registry
        r = registry if registry is not None else default_registry()
        self._m_actions = r.counter(
            "elastic_actions_total",
            "capacity-plan recommendations the elastic actuator "
            "actually executed, by action — the r20 plane recommends, "
            "this counts actuation",
            labels=("action",))
        self._action_children = {
            a: self._m_actions.labels(action=a)
            for a in ("scale_up", "scale_down", "rebalance")}
        self._m_drained = r.counter(
            "elastic_drained_requests_total",
            "in-flight requests drained off a scale_down victim, by "
            "how they travelled: 'migrated' = KV pages extracted and "
            "re-injected (zero re-prefill), 're_prefilled' = the r15 "
            "recompute fallback",
            labels=("fate",))
        self._m_warm = r.counter(
            "elastic_warmup_restored_pages_total",
            "host-tier prefix pages copied into a freshly admitted "
            "engine's tier during scale_up warmup (hot families "
            "pre-seeded so first touches restore instead of recompute)")

    # ---- the one per-step hook ------------------------------------------
    def step(self) -> Optional[str]:
        """Read the committed plan and act on it (at most one action).
        Returns the action executed this call, or None."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        plan = self.router.capacity_plan()
        action = plan.get("action", "steady")
        if action == "steady":
            return None
        # one actuation per planner evaluation: the recommendation
        # persists until its clear band, and re-acting on the same
        # evaluation would double-execute one decision
        if plan.get("evaluations", 0) == self._acted_evaluations:
            return None
        executed = None
        if action == "scale_up":
            executed = self._scale_up()
        elif action == "scale_down":
            executed = self._scale_down()
        elif action == "rebalance":
            executed = self._rebalance(plan)
        if executed is not None:
            self._acted_evaluations = plan.get("evaluations", 0)
            self._cooldown = self.cooldown_steps
            self._action_children[executed].inc()
        return executed

    # ---- scale_up --------------------------------------------------------
    def _scale_up(self) -> Optional[str]:
        if len(self.router.handles) >= self.max_engines:
            return None
        engine = self.standby.pop() if self.standby else (
            self.spawn() if self.spawn is not None else None)
        if engine is None:
            return None
        eid = self.router.add_engine(engine)
        detail = {"engine": eid,
                  "warmed_pages": self._warm_host_tier(eid),
                  "shed": self._shed_into_pool(limit=(
                      self.max_moves_per_action))}
        self.actions.append((self._evaluations(), "scale_up", detail))
        return "scale_up"

    def _warm_host_tier(self, cold_id: int) -> int:
        """Copy the hottest host-tier prefix entries from saturated
        peers into the cold engine's tier (digest keys are engine-
        independent — the r19 chain digest hashes prompt tokens only).
        The newcomer's first prompts then restore from host RAM via
        the normal ``match(restore=True)`` path instead of
        recomputing.  Returns pages copied."""
        h = self.router.handles.get(cold_id)
        cold = getattr(h, "engine", None)
        tier = getattr(cold, "host_tier", None)
        geo_fn = getattr(cold, "migration_geometry", None)
        if tier is None or geo_fn is None or not self.warm_pages:
            return 0
        try:
            cold_geo = geo_fn()
        except Exception:                             # noqa: BLE001
            return 0
        if cold_geo is None:
            return 0
        copied = 0
        for peer_id in self._by_saturation(descending=True):
            if peer_id == cold_id or copied >= self.warm_pages:
                break
            ph = self.router.handles.get(peer_id)
            src = getattr(getattr(ph, "engine", None), "host_tier",
                          None)
            pgeo = getattr(ph.engine, "migration_geometry",
                           lambda: None)()
            if src is None or pgeo != cold_geo:
                continue
            # hottest first: the LRU keeps most-recently-touched at
            # the back
            for key in list(reversed(src.entries)):
                if copied >= self.warm_pages:
                    break
                if key in tier:
                    continue
                buf = src.entries.get(key)
                if buf is not None and tier.put(key, buf):
                    copied += 1
        if copied:
            self._m_warm.inc(copied)
        return copied

    def _shed_into_pool(self, limit: int) -> int:
        """Pull decoding requests off the most-saturated peer so their
        pages migrate to wherever the ranked dispatch finds spare
        capacity — right after a scale_up that is the empty newcomer."""
        order = self._by_saturation(descending=True)
        return self._shed_from(order[0], limit) if order else 0

    # ---- scale_down ------------------------------------------------------
    def _scale_down(self) -> Optional[str]:
        if len(self.router.handles) <= self.min_engines:
            return None
        order = self._by_saturation(descending=False)
        victim = next((eid for eid in order
                       if len(self.router.handles) > 1), None)
        if victim is None:
            return None
        engine = self.router.handles[victim].engine
        fates = self.router.remove_engine(victim, reason="scale_down")
        self._m_drained.labels(fate="migrated").inc(fates["migrated"])
        self._m_drained.labels(fate="re_prefilled").inc(
            fates["re_prefilled"])
        if self.retire is not None:
            self.retire(engine)
        else:
            self.standby.append(engine)
        self.actions.append((self._evaluations(), "scale_down",
                             {"engine": victim, "fates": fates}))
        return "scale_down"

    # ---- rebalance -------------------------------------------------------
    def _rebalance(self, plan: Dict) -> Optional[str]:
        pairs = plan.get("rebalance_pairs") or []
        moved = 0
        for pair in pairs:
            if moved >= self.max_moves_per_action:
                break
            moved += self._shed_from(
                pair["source_engine"],
                self.max_moves_per_action - moved,
                prefer=pair.get("target_engine"))
        if not moved:
            return None
        self.actions.append((self._evaluations(), "rebalance",
                             {"moved": moved}))
        return "rebalance"

    # ---- shared machinery ------------------------------------------------
    def _shed_from(self, src_id: int, limit: int,
                   prefer: Optional[int] = None) -> int:
        """Extract up to ``limit`` decoding requests off ``src_id`` and
        requeue them with their KV pages (``reason="rebalance"``) —
        the router's next dispatch injects them wherever capacity and
        geometry line up (``prefer`` only gates on that engine having
        room; placement stays the ranked dispatch's call — the
        unchanged state machine is the point)."""
        router = self.router
        h = router.handles.get(src_id)
        if h is None or not h.healthy:
            return 0
        geo_fn = getattr(h.engine, "migration_geometry", None)
        src_geo = geo_fn() if geo_fn is not None else None
        if src_geo is None:
            return 0
        if prefer is not None:
            th = router.handles.get(prefer)
            if th is None or not th.healthy or not th.has_capacity():
                return 0
        # a target with room and matching pool geometry must exist, or
        # the "move" degrades to paying the prefill again elsewhere
        if not any(t.healthy and t.engine_id != src_id
                   and t.has_capacity()
                   and getattr(t.engine, "migration_geometry",
                               lambda: None)() == src_geo
                   for t in router.handles.values()):
            return 0
        moved = 0
        for key in list(router._inflight.keys()):
            if moved >= limit:
                break
            if key[0] != src_id:
                continue
            rr = router._inflight.get(key)
            ereq = rr.engine_req if rr is not None else None
            if ereq is None or getattr(ereq, "state", "") != "running":
                continue
            if not getattr(ereq, "output_ids", None):
                continue          # prefill not done: nothing to move
            try:
                _prompt, gen, buf = h.engine.extract_request(key[1])
            except Exception:                         # noqa: BLE001
                continue
            router._inflight.pop(key, None)
            rr.migrations += 1
            router._requeue(rr, gen, reason="rebalance", buffer=buf)
            moved += 1
        return moved

    def _by_saturation(self, descending: bool) -> List[int]:
        """Healthy engine ids ordered by their monitored saturation
        EWMA (ties: engine id, for determinism)."""
        cap = self.router.capacity
        out = []
        for h in self.router.handles.values():
            if not h.healthy:
                continue
            m = cap.engines.get(h.engine_id)
            s = m.w_saturation.ewma() if m is not None else None
            out.append((float(s) if s is not None else 0.0,
                        h.engine_id))
        out.sort(key=lambda t: ((-t[0]) if descending else t[0], t[1]))
        return [eid for _s, eid in out]

    def _evaluations(self) -> int:
        return int(self.router.capacity.planner.evaluations)
