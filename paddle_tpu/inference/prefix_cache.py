"""Copy-on-write prefix caching over refcounted KV pages.

Parity intent: vLLM-style automatic prefix caching / the RadixAttention
idea, mapped onto this repo's paged serving stack (Ragged Paged
Attention, arXiv:2604.15464: TPU serving throughput hinges on keeping
KV in reusable pages).  Two requests that share a system prompt should
neither recompute nor duplicate the shared KV.

Design: a hash table at BLOCK granularity.  For every full page of a
finished prefill, the engine registers ``hash(prompt[:end]) -> block``
(the key hashes the whole token prefix up to that page's end, so a hit
chain is position-exact by construction).  An admitted request walks
its own prompt's chain; every consecutive hit is shared into its block
table (``PagedKVCache.share_blocks`` — refcount++) and only the suffix
is prefilled.  The table holds its own reference on each registered
page, so cached prefixes survive the request that created them.

Copy-on-write: a hit that covers the WHOLE prompt is capped one token
short (the last position must be re-run to produce the first sampled
token), which lands the suffix write mid-page — the engine copies that
one shared page to a private one (``serving_step.copy_block``) before
writing.  Aligned hits write only fresh pages and never copy.

Eviction honors refcounts: when the pool runs dry the engine asks for
reclaim, and only table entries whose page has NO other holder
(refcount == 1, the table's own) are dropped; a prefix page some live
request still addresses is never recycled from under it.  Entries are
dropped oldest-touch first (LRU); evicting a chain's parent merely
makes longer entries unreachable for matching — they stay individually
evictable.  Entries SKIPPED because a live request still pins their
page are counted (``skipped_pinned``) so cache-pressure stalls are
diagnosable from the eviction metric's outcome label.

Host-RAM spill tier (round 19): with a :class:`HostPageTier` attached,
an evicted-but-hot prefix page doesn't die — its KV (int8 codes plus
per-page scale rows, 3.9× denser than fp32) is serialized to a
bounded, byte-capped host LRU in ONE batched device→host copy
(``jit/serving_step.extract_blocks``) before the device page returns
to the free list.  A later ``match`` whose device chain breaks probes
the tier for the continuation and restores every consecutive spilled
page with ONE ``inject_blocks`` dispatch — the pages re-enter the
table under the SAME blake2b digest chain, so prefix capacity is
bounded by host RAM instead of one engine's HBM.  Restores never evict
(they only consume already-free device pages), so a full pool degrades
to plain misses instead of thrashing spill↔restore.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Set

import numpy as np

__all__ = ["PrefixPageCache", "HostPageTier"]


def _prefix_key(prompt_ids: np.ndarray, end: int) -> bytes:
    return hashlib.blake2b(
        np.ascontiguousarray(prompt_ids[:end], dtype=np.int64).tobytes(),
        digest_size=16).digest()


class HostPageTier:
    """Bounded host-RAM LRU of spilled prefix pages: digest key → a
    1-page :class:`~paddle_tpu.ops.paged_attention.KVPageBuffer`.
    Byte-capped (``capacity_bytes``), oldest-touch evicted — the spill
    tier is a cache of a cache, so dropping an entry is always safe."""

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = int(capacity_bytes)
        self.entries: "OrderedDict[bytes, object]" = OrderedDict()
        self.bytes = 0
        # spilled-then-aged-out entries (distinct from device eviction)
        self.tier_evictions = 0

    def put(self, key: bytes, buf) -> bool:
        """Insert/replace one spilled page; evicts LRU entries until
        the tier fits its byte cap.  Returns False (and stores
        nothing) when the single entry alone exceeds the cap."""
        if buf.nbytes > self.capacity_bytes:
            return False
        old = self.entries.pop(key, None)
        if old is not None:
            self.bytes -= old.nbytes
        self.entries[key] = buf
        self.bytes += buf.nbytes
        while self.bytes > self.capacity_bytes and self.entries:
            _k, dropped = self.entries.popitem(last=False)
            self.bytes -= dropped.nbytes
            self.tier_evictions += 1
        return True

    def get(self, key: bytes):
        buf = self.entries.get(key)
        if buf is not None:
            self.entries.move_to_end(key)
        return buf

    def pop(self, key: bytes):
        buf = self.entries.pop(key, None)
        if buf is not None:
            self.bytes -= buf.nbytes
        return buf

    def __contains__(self, key: bytes) -> bool:
        return key in self.entries

    def __len__(self) -> int:
        return len(self.entries)


class PrefixPageCache:
    """Block-granularity prompt-prefix table over one ``PagedKVCache``
    free-list authority (the engine's layer-0 cache: block ids are
    shared across layers).

    ``all_caches`` (the engine's full per-layer cache list) plus
    ``host_tier`` arm the round-19 spill tier: eviction serializes the
    dropped pages to host RAM, ``match`` restores them on a later hit
    — both as single batched transfers."""

    def __init__(self, cache, block_size: int, all_caches=None,
                 host_tier: Optional[HostPageTier] = None):
        self.cache = cache
        self.block_size = block_size
        self.all_caches = all_caches
        self.host_tier = host_tier
        if host_tier is not None and not all_caches:
            raise ValueError(
                "PrefixPageCache host_tier needs all_caches (the "
                "engine's full per-layer cache list): spill/restore "
                "moves every layer's copy of a page, not just the "
                "free-list authority's")
        self.table: "OrderedDict[bytes, int]" = OrderedDict()
        self._registered: Set[int] = set()   # block ids the table refs
        # host-side stats (the engine mirrors these into the metrics
        # registry; kept here too so benches can read them directly)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0
        self.skipped_pinned = 0     # evict() passes over a pinned entry
        self.spills = 0             # pages serialized to the host tier
        self.host_hits = 0          # lookups that found a spilled page
        self.restores = 0           # spilled pages injected back

    # ---- lookup ---------------------------------------------------------
    def match(self, prompt_ids: np.ndarray,
              restore: bool = True) -> List[int]:
        """Longest consecutive chain of cached full-page prefixes of
        ``prompt_ids``.  With a host tier attached (and ``restore``),
        a chain that breaks on the device table continues into the
        spill tier: every consecutive spilled page is restored with ONE
        batched inject and re-registered — bounded by the free list
        (restores never evict).  Otherwise side-effect free except LRU
        touch; the caller decides whether to commit (share_blocks) the
        hit."""
        bs = self.block_size
        prompt_ids = np.asarray(prompt_ids)
        blocks: List[int] = []
        n_full = len(prompt_ids) // bs
        for i in range(n_full):
            key = _prefix_key(prompt_ids, (i + 1) * bs)
            b = self.table.get(key)
            if b is None:
                break
            self.table.move_to_end(key)
            blocks.append(b)
        if restore and self.host_tier is not None:
            blocks.extend(
                self._restore_chain(prompt_ids, len(blocks), n_full))
        return blocks

    def _restore_chain(self, prompt_ids, start: int,
                       n_full: int) -> List[int]:
        """Continue a broken device chain out of the host tier: probe
        keys ``start..``, restore every consecutive hit (capped by the
        free list) with one ``inject_blocks`` dispatch, re-register
        each page under its digest (the table takes the allocated
        reference, exactly like a registered page)."""
        bs = self.block_size
        pending = []
        for i in range(start, n_full):
            key = _prefix_key(prompt_ids, (i + 1) * bs)
            ent = self.host_tier.get(key)
            if ent is None:
                break
            pending.append((key, ent))
        if not pending:
            return []
        self.host_hits += len(pending)
        # restores never evict: only already-free device pages are used
        pending = pending[:len(self.cache._free)]
        if not pending:
            return []
        from ..jit.serving_step import inject_blocks
        from ..ops.paged_attention import KVPageBuffer
        first = pending[0][1]
        combined = KVPageBuffer(
            codes=np.concatenate([e.codes for _, e in pending], axis=1),
            scales=(np.concatenate([e.scales for _, e in pending],
                                   axis=1)
                    if first.scales is not None else None),
            n_pages=len(pending),
            n_tokens=len(pending) * self.block_size,
            block_size=first.block_size,
            num_kv_heads=first.num_kv_heads, head_dim=first.head_dim,
            num_layers=first.num_layers, kv_dtype=first.kv_dtype)
        dest = [self.cache.allocate_block() for _ in pending]
        inject_blocks(self.all_caches, combined, dest)
        out: List[int] = []
        for (key, _ent), b in zip(pending, dest):
            self.host_tier.pop(key)
            self.table[key] = b
            self._registered.add(b)
            self.table.move_to_end(key)
            out.append(b)
        self.restores += len(pending)
        return out

    # ---- registration ---------------------------------------------------
    def register(self, prompt_ids: np.ndarray, block_ids: List[int]):
        """Publish a freshly prefilled prompt's FULL pages.  Keys already
        present keep their existing page (first writer wins); the table
        takes its own reference on each newly published page."""
        bs = self.block_size
        prompt_ids = np.asarray(prompt_ids)
        for i in range(len(prompt_ids) // bs):
            if i >= len(block_ids):
                break
            b = int(block_ids[i])
            key = _prefix_key(prompt_ids, (i + 1) * bs)
            if key in self.table or b in self._registered:
                continue
            self.cache.share_blocks([b])
            self.table[key] = b
            self._registered.add(b)
            self.table.move_to_end(key)

    # ---- eviction -------------------------------------------------------
    def evictable_count(self, exclude: Optional[Set[int]] = None) -> int:
        """Pages reclaimable right now: table entries no live request
        holds (refcount == 1 — the table's own reference)."""
        exclude = exclude or set()
        return sum(1 for b in self.table.values()
                   if b not in exclude and self.cache.refcount(b) == 1)

    def evict(self, n: int = 1) -> int:
        """Drop up to ``n`` LRU entries whose page has no other holder,
        returning their pages to the free list.  Entries whose page is
        still shared with a live request are SKIPPED (never reclaimed
        from under a block table) and counted in ``skipped_pinned`` —
        the engine surfaces both outcomes on the eviction counter's
        label so cache-pressure stalls are diagnosable.

        With a host tier attached, the victims' pages are serialized
        to host RAM FIRST — all of them in ONE batched device→host
        extract — then released; a later ``match`` restores them."""
        victims = []
        for key in list(self.table.keys()):
            if len(victims) >= n:
                break
            b = self.table[key]
            if self.cache.refcount(b) != 1:
                self.skipped_pinned += 1
                continue
            victims.append((key, b))
        if victims and self.host_tier is not None:
            self._spill(victims)
        for key, b in victims:
            del self.table[key]
            self._registered.discard(b)
            self.cache.free_sequence([b])
            self.evictions += 1
        return len(victims)

    def _spill(self, victims) -> None:
        """Serialize the victim pages to the host tier: ONE batched
        extract over all of them, split host-side into per-key 1-page
        entries (so any subset restores independently)."""
        from ..jit.serving_step import extract_blocks
        from ..ops.paged_attention import KVPageBuffer
        bs = self.block_size
        buf = extract_blocks(self.all_caches, [b for _, b in victims],
                             n_tokens=len(victims) * bs)
        for i, (key, _b) in enumerate(victims):
            entry = KVPageBuffer(
                codes=np.ascontiguousarray(buf.codes[:, i:i + 1]),
                scales=(np.ascontiguousarray(buf.scales[:, i:i + 1])
                        if buf.scales is not None else None),
                n_pages=1, n_tokens=bs, block_size=buf.block_size,
                num_kv_heads=buf.num_kv_heads, head_dim=buf.head_dim,
                num_layers=buf.num_layers, kv_dtype=buf.kv_dtype)
            if self.host_tier.put(key, entry):
                self.spills += 1

    # ---- introspection --------------------------------------------------
    def cached_blocks(self) -> Set[int]:
        return set(self.table.values())

    def __len__(self) -> int:
        return len(self.table)
