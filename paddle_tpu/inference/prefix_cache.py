"""Copy-on-write prefix caching over refcounted KV pages.

Parity intent: vLLM-style automatic prefix caching / the RadixAttention
idea, mapped onto this repo's paged serving stack (Ragged Paged
Attention, arXiv:2604.15464: TPU serving throughput hinges on keeping
KV in reusable pages).  Two requests that share a system prompt should
neither recompute nor duplicate the shared KV.

Design: a hash table at BLOCK granularity.  For every full page of a
finished prefill, the engine registers ``hash(prompt[:end]) -> block``
(the key hashes the whole token prefix up to that page's end, so a hit
chain is position-exact by construction).  An admitted request walks
its own prompt's chain; every consecutive hit is shared into its block
table (``PagedKVCache.share_blocks`` — refcount++) and only the suffix
is prefilled.  The table holds its own reference on each registered
page, so cached prefixes survive the request that created them.

Copy-on-write: a hit that covers the WHOLE prompt is capped one token
short (the last position must be re-run to produce the first sampled
token), which lands the suffix write mid-page — the engine copies that
one shared page to a private one (``serving_step.copy_block``) before
writing.  Aligned hits write only fresh pages and never copy.

Eviction honors refcounts: when the pool runs dry the engine asks for
reclaim, and only table entries whose page has NO other holder
(refcount == 1, the table's own) are dropped; a prefix page some live
request still addresses is never recycled from under it.  Entries are
dropped oldest-touch first (LRU); evicting a chain's parent merely
makes longer entries unreachable for matching — they stay individually
evictable.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Set

import numpy as np

__all__ = ["PrefixPageCache"]


def _prefix_key(prompt_ids: np.ndarray, end: int) -> bytes:
    return hashlib.blake2b(
        np.ascontiguousarray(prompt_ids[:end], dtype=np.int64).tobytes(),
        digest_size=16).digest()


class PrefixPageCache:
    """Block-granularity prompt-prefix table over one ``PagedKVCache``
    free-list authority (the engine's layer-0 cache: block ids are
    shared across layers)."""

    def __init__(self, cache, block_size: int):
        self.cache = cache
        self.block_size = block_size
        self.table: "OrderedDict[bytes, int]" = OrderedDict()
        self._registered: Set[int] = set()   # block ids the table refs
        # host-side stats (the engine mirrors these into the metrics
        # registry; kept here too so benches can read them directly)
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.evictions = 0

    # ---- lookup ---------------------------------------------------------
    def match(self, prompt_ids: np.ndarray) -> List[int]:
        """Longest consecutive chain of cached full-page prefixes of
        ``prompt_ids``.  Side-effect free except LRU touch; the caller
        decides whether to commit (share_blocks) the hit."""
        bs = self.block_size
        prompt_ids = np.asarray(prompt_ids)
        blocks: List[int] = []
        for i in range(len(prompt_ids) // bs):
            key = _prefix_key(prompt_ids, (i + 1) * bs)
            b = self.table.get(key)
            if b is None:
                break
            self.table.move_to_end(key)
            blocks.append(b)
        return blocks

    # ---- registration ---------------------------------------------------
    def register(self, prompt_ids: np.ndarray, block_ids: List[int]):
        """Publish a freshly prefilled prompt's FULL pages.  Keys already
        present keep their existing page (first writer wins); the table
        takes its own reference on each newly published page."""
        bs = self.block_size
        prompt_ids = np.asarray(prompt_ids)
        for i in range(len(prompt_ids) // bs):
            if i >= len(block_ids):
                break
            b = int(block_ids[i])
            key = _prefix_key(prompt_ids, (i + 1) * bs)
            if key in self.table or b in self._registered:
                continue
            self.cache.share_blocks([b])
            self.table[key] = b
            self._registered.add(b)
            self.table.move_to_end(key)

    # ---- eviction -------------------------------------------------------
    def evictable_count(self, exclude: Optional[Set[int]] = None) -> int:
        """Pages reclaimable right now: table entries no live request
        holds (refcount == 1 — the table's own reference)."""
        exclude = exclude or set()
        return sum(1 for b in self.table.values()
                   if b not in exclude and self.cache.refcount(b) == 1)

    def evict(self, n: int = 1) -> int:
        """Drop up to ``n`` LRU entries whose page has no other holder,
        returning their pages to the free list.  Entries whose page is
        still shared with a live request are SKIPPED (never reclaimed
        from under a block table)."""
        freed = 0
        for key in list(self.table.keys()):
            if freed >= n:
                break
            b = self.table[key]
            if self.cache.refcount(b) != 1:
                continue
            del self.table[key]
            self._registered.discard(b)
            self.cache.free_sequence([b])
            self.evictions += 1
            freed += 1
        return freed

    # ---- introspection --------------------------------------------------
    def cached_blocks(self) -> Set[int]:
        return set(self.table.values())

    def __len__(self) -> int:
        return len(self.table)
