"""paddle.hub (parity: python/paddle/hub.py — list/help/load over github/
gitee/local sources).  Only the 'local' source works here (no egress)."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUB_CONF = "hubconf.py"


def _load_local(repo_dir):
    path = os.path.join(repo_dir, _HUB_CONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUB_CONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise ValueError(
            "only source='local' is supported in this environment "
            "(github/gitee need network egress)")


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_local(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    return getattr(_load_local(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_local(repo_dir), model)(**kwargs)
