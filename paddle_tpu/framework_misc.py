"""Top-level framework misc: iinfo/finfo, ParamAttr, flops.

Parity: python/paddle/framework/dtype.py (iinfo/finfo), python/paddle/
base/param_attr.py (ParamAttr), python/paddle/hapi/dynamic_flops.py
(paddle.flops)."""
from __future__ import annotations

from typing import Optional

import numpy as np

from .core import dtypes as _dt


class _DTypeInfo:
    def __init__(self, npinfo, dtype_name):
        is_float = hasattr(npinfo, "eps")
        # iinfo bounds stay EXACT python ints (float64 cannot represent
        # int64 max and would overflow on round-trip)
        cast = float if is_float else int
        self.min = cast(npinfo.min)
        self.max = cast(npinfo.max)
        self.bits = npinfo.bits
        self.dtype = dtype_name
        if is_float:
            self.eps = float(npinfo.eps)
            self.tiny = float(npinfo.tiny)
            self.smallest_normal = float(npinfo.smallest_normal)
            self.resolution = float(npinfo.resolution)

    def __repr__(self):
        return (f"{type(self).__name__}(min={self.min}, max={self.max}, "
                f"bits={self.bits}, dtype={self.dtype})")


def iinfo(dtype):
    """Parity: paddle.iinfo."""
    d = np.dtype(str(_dt.convert_dtype(dtype)))
    return _DTypeInfo(np.iinfo(d), d.name)


def finfo(dtype):
    """Parity: paddle.finfo (incl. bfloat16 via ml_dtypes)."""
    import jax.numpy as jnp
    d = _dt.convert_dtype(dtype)
    try:
        return _DTypeInfo(np.finfo(d), np.dtype(d).name)
    except Exception:
        return _DTypeInfo(jnp.finfo(d), str(d))


class ParamAttr:
    """Parity: paddle.ParamAttr (base/param_attr.py) — parameter config
    holder consumed by Layer.create_parameter."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Parity: paddle.flops (hapi/dynamic_flops.py) — per-layer
    multiply-add count via forward hooks (the reference's convention:
    one MAC = one FLOP)."""
    from .core.tensor import Tensor
    from . import nn

    counts = {}
    handles = []

    def count(layer, name):
        def hook(l, inputs, output):
            x = inputs[0] if isinstance(inputs, (list, tuple)) else inputs
            n = 0
            if isinstance(l, nn.Linear):
                n = int(np.prod(x.shape[:-1])) * l.weight.shape[0] \
                    * l.weight.shape[1]
            elif hasattr(l, "weight") and l.__class__.__name__.startswith(
                    "Conv"):
                w = l.weight
                out_elems = int(np.prod(output.shape))
                k_elems = int(np.prod(w.shape[1:]))
                n = out_elems * k_elems
            elif l.__class__.__name__.startswith("BatchNorm"):
                n = int(np.prod(x.shape))
            if custom_ops and type(l) in custom_ops:
                n = custom_ops[type(l)](l, x, output)
            counts[name] = counts.get(name, 0) + n
        return hook

    for name, sub in net.named_sublayers():
        if not list(sub.named_children()):    # leaves only
            handles.append(sub.register_forward_post_hook(
                count(sub, name or sub.__class__.__name__)))
    import jax.numpy as jnp
    x = Tensor(np.zeros(input_size, np.float32))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        if was_training:
            net.train()
        for h in handles:
            try:
                h.remove()
            except Exception:
                pass
    total = int(sum(counts.values()))
    if print_detail:
        for k, v in counts.items():
            print(f"  {k}: {v:,}")
        print(f"Total FLOPs: {total:,}")
    return total
