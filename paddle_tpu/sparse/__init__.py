"""paddle.sparse (parity: python/paddle/sparse/ — sparse_coo_tensor
creation.py:72, sparse_csr_tensor :185, unary/binary ops, sparse matmul,
nn activations; backing C++ types SparseCooTensor/SparseCsrTensor in
paddle/phi/core/).

TPU-native: SparseCooTensor wraps jax.experimental.sparse.BCOO — the XLA
sparse format whose ops lower to gather/scatter/dot_general on the MXU;
CSR is kept as a view-convention on top of the same BCOO data (XLA has no
native CSR kernels; the reference's CSR kernels are CPU/cuSPARSE).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from .. import nn as _nn_mod

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "is_sparse_coo", "is_sparse_csr",
           "add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul", "relu", "sin", "tanh", "abs", "sqrt",
           "square", "log1p", "neg", "cast", "transpose", "coalesce",
           "nn"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over BCOO (parity: phi::SparseCooTensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-like surface --
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor._from_value(
            jnp.swapaxes(self._bcoo.indices, 0, 1).astype(jnp.int64))

    def values(self) -> Tensor:
        return Tensor._from_value(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor._from_value(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(self._bcoo)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(
            self._bcoo.sum_duplicates(remove_zeros=False))

    def astype(self, dtype):
        from ..core.dtypes import convert_dtype
        return SparseCooTensor(jsparse.BCOO(
            (self._bcoo.data.astype(convert_dtype(dtype)),
             self._bcoo.indices), shape=self._bcoo.shape))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor(SparseCooTensor):
    """CSR view (parity: phi::SparseCsrTensor). Data is shared BCOO; the
    crows/cols accessors materialize the CSR index arrays."""

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def crows(self) -> Tensor:
        rows = np.asarray(self._sorted().indices[:, 0])
        n_rows = self.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        for r in rows:
            crows[int(r) + 1] += 1
        return Tensor(np.cumsum(crows))

    def cols(self) -> Tensor:
        return Tensor(np.asarray(self._sorted().indices[:, 1],
                                 dtype=np.int64))

    def values(self) -> Tensor:
        return Tensor._from_value(self._sorted().data)

    def _sorted(self):
        idx = self._bcoo.indices
        order = jnp.lexsort((idx[:, 1], idx[:, 0]))
        return jsparse.BCOO((self._bcoo.data[order], idx[order]),
                            shape=self._bcoo.shape)

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcoo)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_coo_tensor (creation.py:72).
    indices: [sparse_dim, nnz]; values: [nnz, ...]."""
    idx = np.asarray(_v(indices), np.int32)
    vals = _v(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype
        vals = jnp.asarray(vals, convert_dtype(dtype))
    else:
        vals = jnp.asarray(vals)
    if shape is None:
        dense_dims = (vals.ndim - 1)
        sp_shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = sp_shape + tuple(vals.shape[1:])
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T, jnp.int32)),
                        shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_csr_tensor (creation.py:185)."""
    crows = np.asarray(_v(crows), np.int64)
    cols = np.asarray(_v(cols), np.int64)
    vals = _v(values)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    indices = np.stack([rows, cols])
    t = sparse_coo_tensor(indices, vals, shape, dtype)
    return SparseCsrTensor(t._bcoo)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor) and x.is_sparse_coo()


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


# ---------------------------------------------------------------------------
# binary ops
# ---------------------------------------------------------------------------
def _wrap_same(x: SparseCooTensor, bcoo):
    return (SparseCsrTensor(bcoo) if isinstance(x, SparseCsrTensor)
            else SparseCooTensor(bcoo))


def _binary(x, y, op):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        dense = op(x._bcoo.todense(), y._bcoo.todense())
        return _wrap_same(x, jsparse.BCOO.fromdense(dense))
    if isinstance(x, SparseCooTensor):
        return Tensor._from_value(op(x._bcoo.todense(), _v(y)))
    return Tensor._from_value(op(_v(x), y._bcoo.todense()))


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor) \
            and not isinstance(x, SparseCsrTensor):
        # structural add stays sparse without densifying
        data = jnp.concatenate([x._bcoo.data, y._bcoo.data])
        idx = jnp.concatenate([x._bcoo.indices, y._bcoo.indices])
        out = jsparse.BCOO((data, idx),
                           shape=x._bcoo.shape).sum_duplicates()
        return SparseCooTensor(out)
    return _binary(x, y, jnp.add)


def subtract(x, y, name=None):
    return _binary(x, y, jnp.subtract)


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and not isinstance(
            y, SparseCooTensor) and jnp.ndim(_v(y)) == 0:
        return _wrap_same(x, jsparse.BCOO(
            (x._bcoo.data * _v(y), x._bcoo.indices), shape=x._bcoo.shape))
    return _binary(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _binary(x, y, jnp.divide)


def matmul(x, y, name=None):
    """sparse @ dense / sparse @ sparse (parity: paddle.sparse.matmul).
    BCOO dot lowers to XLA dot_general with gathers — MXU-eligible."""
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = x._bcoo @ y._bcoo.todense()
        return Tensor._from_value(out)
    if isinstance(x, SparseCooTensor):
        return Tensor._from_value(x._bcoo @ _v(y))
    if isinstance(y, SparseCooTensor):
        return Tensor._from_value(_v(x) @ y._bcoo)
    return Tensor._from_value(_v(x) @ _v(y))


def masked_matmul(x, y, mask: SparseCooTensor, name=None):
    """(x @ y) sampled at mask's sparsity (parity: SDDMM)."""
    xv, yv = _v(x), _v(y)
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, idx),
                                        shape=mask._bcoo.shape))


# ---------------------------------------------------------------------------
# unary ops (value-wise; zeros preserved)
# ---------------------------------------------------------------------------
def _unary(x, op):
    if isinstance(x, SparseCooTensor):
        return _wrap_same(x, jsparse.BCOO((op(x._bcoo.data),
                                           x._bcoo.indices),
                                          shape=x._bcoo.shape))
    return Tensor._from_value(op(_v(x)))


def relu(x, name=None):
    return _unary(x, jax.nn.relu)


def sin(x, name=None):
    return _unary(x, jnp.sin)


def tanh(x, name=None):
    return _unary(x, jnp.tanh)


def abs(x, name=None):
    return _unary(x, jnp.abs)


def sqrt(x, name=None):
    return _unary(x, jnp.sqrt)


def square(x, name=None):
    return _unary(x, jnp.square)


def log1p(x, name=None):
    return _unary(x, jnp.log1p)


def neg(x, name=None):
    return _unary(x, jnp.negative)


def pow(x, factor, name=None):
    return _unary(x, lambda v: jnp.power(v, factor))


def expm1(x, name=None):
    return _unary(x, jnp.expm1)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if value_dtype is not None:
        return x.astype(value_dtype)
    return x


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        idx = x._bcoo.indices[:, jnp.asarray(perm, jnp.int32)]
        shape = tuple(x._bcoo.shape[p] for p in perm)
        return _wrap_same(x, jsparse.BCOO((x._bcoo.data, idx),
                                          shape=shape))
    return Tensor._from_value(jnp.transpose(_v(x), perm))


def coalesce(x, name=None):
    return x.coalesce()


# ---------------------------------------------------------------------------
# sparse.nn (activations as layers — parity: python/paddle/sparse/nn)
# ---------------------------------------------------------------------------
class _SparseActLayer:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x):
        return self._fn(x)


class nn:
    class ReLU(_SparseActLayer):
        def __init__(self):
            super().__init__(relu)

    class Softmax:
        """Row-wise softmax over CSR rows (parity: sparse/nn softmax)."""

        def __init__(self, axis=-1):
            pass

        def __call__(self, x: SparseCooTensor):
            idx = x._bcoo.indices
            rows = idx[:, 0]
            data = x._bcoo.data
            n_rows = x.shape[0]
            row_max = jnp.full((n_rows,), -jnp.inf).at[rows].max(data)
            e = jnp.exp(data - row_max[rows])
            denom = jnp.zeros((n_rows,)).at[rows].add(e)
            return _wrap_same(x, jsparse.BCOO((e / denom[rows], idx),
                                              shape=x._bcoo.shape))
