"""paddle.sparse (parity: python/paddle/sparse/ — sparse_coo_tensor
creation.py:72, sparse_csr_tensor :185, unary/binary ops, sparse matmul,
nn activations; backing C++ types SparseCooTensor/SparseCsrTensor in
paddle/phi/core/).

TPU-native: SparseCooTensor wraps jax.experimental.sparse.BCOO — the XLA
sparse format whose ops lower to gather/scatter/dot_general on the MXU;
CSR is kept as a view-convention on top of the same BCOO data (XLA has no
native CSR kernels; the reference's CSR kernels are CPU/cuSPARSE).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from .. import nn as _nn_mod

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor", "is_sparse_coo", "is_sparse_csr",
           "add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul", "relu", "sin", "tanh", "abs", "sqrt",
           "square", "log1p", "neg", "cast", "transpose", "coalesce",
           "nn"]


def _v(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over BCOO (parity: phi::SparseCooTensor)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # -- paddle Tensor-like surface --
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor._from_value(
            jnp.swapaxes(self._bcoo.indices, 0, 1).astype(jnp.int64))

    def values(self) -> Tensor:
        t = getattr(self, "_values_t", None)
        if t is not None:
            return t      # carries tape history from differentiable ops
        return Tensor._from_value(self._bcoo.data)

    def to_dense(self) -> Tensor:
        t = getattr(self, "_values_t", None)
        if t is not None:
            from ..core.dispatch import apply_op
            idx, shp = self._bcoo.indices, self._bcoo.shape
            return apply_op(
                "sparse_to_dense",
                lambda v: jsparse.BCOO((v, idx), shape=shp).todense(),
                (t,))
        return Tensor._from_value(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(self._bcoo)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(
            self._bcoo.sum_duplicates(remove_zeros=False))

    def astype(self, dtype):
        from ..core.dtypes import convert_dtype
        return SparseCooTensor(jsparse.BCOO(
            (self._bcoo.data.astype(convert_dtype(dtype)),
             self._bcoo.indices), shape=self._bcoo.shape))

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")

    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor(SparseCooTensor):
    """CSR view (parity: phi::SparseCsrTensor). Data is shared BCOO; the
    crows/cols accessors materialize the CSR index arrays."""

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def crows(self) -> Tensor:
        rows = np.asarray(self._sorted().indices[:, 0])
        n_rows = self.shape[0]
        crows = np.zeros(n_rows + 1, np.int64)
        for r in rows:
            crows[int(r) + 1] += 1
        return Tensor(np.cumsum(crows))

    def cols(self) -> Tensor:
        return Tensor(np.asarray(self._sorted().indices[:, 1],
                                 dtype=np.int64))

    def values(self) -> Tensor:
        t = getattr(self, "_values_t", None)
        if t is not None:
            # CSR values are row-major sorted: gather through dispatch so
            # tape history survives the reorder
            from ..core.dispatch import apply_op
            idx = self._bcoo.indices
            order = jnp.lexsort((idx[:, 1], idx[:, 0]))
            return apply_op("sparse_csr_sort", lambda v: v[order], (t,))
        return Tensor._from_value(self._sorted().data)

    def _sorted(self):
        idx = self._bcoo.indices
        order = jnp.lexsort((idx[:, 1], idx[:, 0]))
        return jsparse.BCOO((self._bcoo.data[order], idx[order]),
                            shape=self._bcoo.shape)

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcoo)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_coo_tensor (creation.py:72).
    indices: [sparse_dim, nnz]; values: [nnz, ...]."""
    idx = np.asarray(_v(indices), np.int32)
    vals = _v(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype
        vals = jnp.asarray(vals, convert_dtype(dtype))
    else:
        vals = jnp.asarray(vals)
    if shape is None:
        dense_dims = (vals.ndim - 1)
        sp_shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = sp_shape + tuple(vals.shape[1:])
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T, jnp.int32)),
                        shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Parity: paddle.sparse.sparse_csr_tensor (creation.py:185)."""
    crows = np.asarray(_v(crows), np.int64)
    cols = np.asarray(_v(cols), np.int64)
    vals = _v(values)
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    indices = np.stack([rows, cols])
    t = sparse_coo_tensor(indices, vals, shape, dtype)
    return SparseCsrTensor(t._bcoo)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor) and x.is_sparse_coo()


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


# ---------------------------------------------------------------------------
# binary ops
# ---------------------------------------------------------------------------
def _wrap_same(x: SparseCooTensor, bcoo):
    return (SparseCsrTensor(bcoo) if isinstance(x, SparseCsrTensor)
            else SparseCooTensor(bcoo))


def _binary(x, y, op):
    from ..core.dispatch import apply_op
    name = f"sparse_{getattr(op, '__name__', 'binary')}"
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        dense_t = apply_op(name, op, (x.to_dense(), y.to_dense()))
        bcoo = jsparse.BCOO.fromdense(dense_t._value,
                                      n_dense=x._bcoo.n_dense)
        idx_np = np.asarray(bcoo.indices)
        sel = tuple(jnp.asarray(idx_np[:, i])
                    for i in range(idx_np.shape[1]))
        vals_t = apply_op(name + "_vals", lambda dv: dv[sel], (dense_t,))
        out = _wrap_same(x, bcoo)
        out._values_t = vals_t
        return out
    if isinstance(x, SparseCooTensor):
        yt = y if isinstance(y, Tensor) else Tensor(_v(y))
        return apply_op(name, op, (x.to_dense(), yt))
    xt = x if isinstance(x, Tensor) else Tensor(_v(x))
    return apply_op(name, op, (xt, y.to_dense()))


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor) \
            and not isinstance(x, SparseCsrTensor):
        # structural add stays sparse without densifying: static
        # coalesce plan + differentiable segment-sum over both value sets
        from ..core.dispatch import apply_op
        idx = np.concatenate([np.asarray(x._bcoo.indices),
                              np.asarray(y._bcoo.indices)])
        uniq, inv = np.unique(idx, axis=0, return_inverse=True)
        inv = jnp.asarray(inv)
        m = uniq.shape[0]

        def fn(xv, yv):
            data = jnp.concatenate([xv, yv])
            return jax.ops.segment_sum(data, inv, num_segments=m)

        vals_t = apply_op("sparse_add", fn,
                          (_values_tensor(x), _values_tensor(y)))
        return _from_values_tensor(x, vals_t,
                                   jnp.asarray(uniq, jnp.int32),
                                   x._bcoo.shape)
    return _binary(x, y, jnp.add)


def subtract(x, y, name=None):
    return _binary(x, y, jnp.subtract)


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and not isinstance(
            y, SparseCooTensor) and jnp.ndim(_v(y)) == 0:
        return _wrap_same(x, jsparse.BCOO(
            (x._bcoo.data * _v(y), x._bcoo.indices), shape=x._bcoo.shape))
    return _binary(x, y, jnp.multiply)


def divide(x, y, name=None):
    return _binary(x, y, jnp.divide)


def matmul(x, y, name=None):
    """sparse @ dense / sparse @ sparse (parity: paddle.sparse.matmul).
    BCOO dot lowers to XLA dot_general with gathers — MXU-eligible.
    Routed through dispatch so gradients flow through sparse pipelines
    (e.g. conv -> matmul)."""
    from ..core.dispatch import apply_op
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        # Structural spGEMM: coo @ coo -> coo (parity:
        # python/paddle/sparse/binary.py matmul returns sparse for
        # sparse x sparse).  The output sparsity pattern and the
        # (a, b) -> out_pos contribution lists depend only on the index
        # structure, so they are computed host-side once; the values
        # flow through dispatch (gather-multiply-scatter with static
        # shapes), keeping the product differentiable in both operands.
        if len(x.shape) != 2 or len(y.shape) != 2 \
                or x._bcoo.n_sparse != 2 or y._bcoo.n_sparse != 2:
            raise NotImplementedError(
                "sparse @ sparse matmul supports 2-D fully-sparse "
                "operands (n_dense/n_batch layouts unsupported)")
        if int(x.shape[1]) != int(y.shape[0]):
            raise ValueError(
                f"sparse matmul shape mismatch: {x.shape} @ {y.shape}")
        xi = np.asarray(x._bcoo.indices)   # [nnzA, 2] rows (i, j)
        yi = np.asarray(y._bcoo.indices)   # [nnzB, 2] rows (j, k)
        n, m = int(x.shape[0]), int(y.shape[1])
        ja, jb = xi[:, 1], yi[:, 0]
        order_b = np.argsort(jb, kind="stable")
        jb_sorted = jb[order_b]
        starts = np.searchsorted(jb_sorted, ja, side="left")
        counts = np.searchsorted(jb_sorted, ja, side="right") - starts
        a_sel = np.repeat(np.arange(len(ja)), counts)
        base = np.repeat(starts, counts)
        local = np.arange(len(a_sel)) - np.repeat(
            np.cumsum(counts) - counts, counts)
        b_sel = order_b[base + local]
        out_keys = xi[a_sel, 0].astype(np.int64) * m + yi[b_sel, 1]
        uniq, out_pos = np.unique(out_keys, return_inverse=True)
        out_idx = np.stack([uniq // m, uniq % m], axis=1)
        nnz_out = len(uniq)
        a_sel_j = jnp.asarray(a_sel)
        b_sel_j = jnp.asarray(b_sel)
        out_pos_j = jnp.asarray(out_pos)

        def fn2(xv, yv):
            contrib = xv[a_sel_j] * yv[b_sel_j]
            return jax.ops.segment_sum(contrib, out_pos_j,
                                       num_segments=nnz_out)

        vals_t = apply_op("sparse_matmul", fn2,
                          (_values_tensor(x), _values_tensor(y)))
        return _from_values_tensor(x, vals_t,
                                   jnp.asarray(out_idx, jnp.int32),
                                   (n, m))
    if isinstance(x, SparseCooTensor):
        xi, xs = x._bcoo.indices, x._bcoo.shape
        yt = y if isinstance(y, Tensor) else Tensor(y)
        if x._bcoo.n_dense:
            # contraction dim is dense: values (nnz, ..., k) @ y then
            # scatter rows at the sparse coords (BCOO dot_general cannot
            # contract dense dims)
            idx_np = np.asarray(xi)
            sel = tuple(jnp.asarray(idx_np[:, i])
                        for i in range(idx_np.shape[1]))

            def fn_d(xv, yv):
                contrib = xv @ yv
                out = jnp.zeros(
                    tuple(xs[: idx_np.shape[1]]) + contrib.shape[1:],
                    contrib.dtype)
                return out.at[sel].add(contrib)

            return apply_op("sparse_matmul", fn_d,
                            (_values_tensor(x), yt))
        return apply_op(
            "sparse_matmul",
            lambda xv, yv: jsparse.BCOO((xv, xi), shape=xs) @ yv,
            (_values_tensor(x), yt))
    if isinstance(y, SparseCooTensor):
        yi, ys = y._bcoo.indices, y._bcoo.shape
        xt = x if isinstance(x, Tensor) else Tensor(x)
        return apply_op(
            "sparse_matmul",
            lambda xv, yv: xv @ jsparse.BCOO((yv, yi), shape=ys),
            (xt, _values_tensor(y)))
    return Tensor._from_value(_v(x) @ _v(y))


def masked_matmul(x, y, mask: SparseCooTensor, name=None):
    """(x @ y) sampled at mask's sparsity (parity: SDDMM)."""
    from ..core.dispatch import apply_op
    idx = mask._bcoo.indices
    rows, cols = idx[:, 0], idx[:, 1]
    xt = x if isinstance(x, Tensor) else Tensor(_v(x))
    yt = y if isinstance(y, Tensor) else Tensor(_v(y))

    def fn(xv, yv):
        return jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)

    vals_t = apply_op("sparse_masked_matmul", fn, (xt, yt))
    out = SparseCooTensor(jsparse.BCOO((vals_t._value, idx),
                                       shape=mask._bcoo.shape))
    out._values_t = vals_t
    return out


# ---------------------------------------------------------------------------
# unary ops (value-wise; zeros preserved)
# ---------------------------------------------------------------------------
def _unary(x, op):
    if isinstance(x, SparseCooTensor):
        # through dispatch so the tape links when x carries history
        return _value_op(x, f"sparse_{getattr(op, '__name__', 'unary')}",
                         op)
    return Tensor._from_value(op(_v(x)))


def relu(x, name=None):
    return _unary(x, jax.nn.relu)


def sin(x, name=None):
    return _unary(x, jnp.sin)


def tanh(x, name=None):
    return _unary(x, jnp.tanh)


def abs(x, name=None):
    return _unary(x, jnp.abs)


def sqrt(x, name=None):
    return _unary(x, jnp.sqrt)


def square(x, name=None):
    return _unary(x, jnp.square)


def log1p(x, name=None):
    return _unary(x, jnp.log1p)


def neg(x, name=None):
    return _unary(x, jnp.negative)


def pow(x, factor, name=None):
    return _unary(x, lambda v: jnp.power(v, factor))


def expm1(x, name=None):
    return _unary(x, jnp.expm1)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    if value_dtype is not None:
        return x.astype(value_dtype)
    return x


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        idx = x._bcoo.indices[:, jnp.asarray(perm, jnp.int32)]
        shape = tuple(x._bcoo.shape[p] for p in perm)
        return _wrap_same(x, jsparse.BCOO((x._bcoo.data, idx),
                                          shape=shape))
    return Tensor._from_value(jnp.transpose(_v(x), perm))


def coalesce(x, name=None):
    return x.coalesce()




# ---------------------------------------------------------------------------
# round-4 op tail: unary completions, sum/reshape/slice, addmm/mv,
# conv3d/maxpool (gather-GEMM-scatter), fused_attention
# (parity: /root/reference/paddle/phi/api/yaml/sparse_ops.yaml, 48 ops;
# kernels /root/reference/paddle/phi/kernels/sparse/)
# ---------------------------------------------------------------------------
def _values_tensor(x: SparseCooTensor) -> Tensor:
    """The tensor view of x's values — carries autograd history when x was
    produced by a differentiable sparse op."""
    t = getattr(x, "_values_t", None)
    if t is None:
        t = Tensor._from_value(x._bcoo.data)
    return t


def _from_values_tensor(like: SparseCooTensor, values_t: Tensor, indices,
                        shape) -> SparseCooTensor:
    out = _wrap_same(like, jsparse.BCOO(
        (values_t._value, indices), shape=tuple(int(s) for s in shape)))
    out._values_t = values_t
    return out


def _value_op(x: SparseCooTensor, name, fn) -> SparseCooTensor:
    """Apply fn to stored values only (the reference's sparse unary
    convention), through dispatch so gradients flow to the values."""
    from ..core.dispatch import apply_op
    out_t = apply_op(name, fn, (_values_tensor(x),))
    return _from_values_tensor(x, out_t, x._bcoo.indices, x._bcoo.shape)


def asin(x, name=None):
    return _value_op(x, "sparse_asin", jnp.arcsin)


def asinh(x, name=None):
    return _value_op(x, "sparse_asinh", jnp.arcsinh)


def atan(x, name=None):
    return _value_op(x, "sparse_atan", jnp.arctan)


def atanh(x, name=None):
    return _value_op(x, "sparse_atanh", jnp.arctanh)


def acos(x, name=None):
    return _value_op(x, "sparse_acos", jnp.arccos)


def acosh(x, name=None):
    return _value_op(x, "sparse_acosh", jnp.arccosh)


def sinh(x, name=None):
    return _value_op(x, "sparse_sinh", jnp.sinh)


def tan(x, name=None):
    return _value_op(x, "sparse_tan", jnp.tan)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_op(x, "sparse_leaky_relu",
                     lambda v: jnp.where(v >= 0, v, negative_slope * v))


def relu6(x, name=None):
    return _value_op(x, "sparse_relu6", lambda v: jnp.clip(v, 0.0, 6.0))


def isnan(x, name=None):
    return _wrap_same(x, jsparse.BCOO(
        (jnp.isnan(x._bcoo.data), x._bcoo.indices), shape=x._bcoo.shape))


def scale(x, scale, bias=0.0, bias_after_scale=True, name=None):
    if bias_after_scale:
        return _value_op(x, "sparse_scale", lambda v: v * scale + bias)
    return _value_op(x, "sparse_scale", lambda v: (v + bias) * scale)


def divide_scalar(x, scalar, name=None):
    return _value_op(x, "sparse_divide_scalar", lambda v: v / scalar)


def full_like(x, fill_value, dtype=None, name=None):
    vals = jnp.full_like(x._bcoo.data, fill_value)
    if dtype is not None:
        from ..core.dtypes import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    return _wrap_same(x, jsparse.BCOO((vals, x._bcoo.indices),
                                      shape=x._bcoo.shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Parity: paddle.sparse.sum (sparse_ops.yaml `sum`).  Axis reduction
    drops the summed coordinate and coalesces duplicates — stays sparse
    like the reference."""
    from ..core.dispatch import apply_op
    from ..core.dtypes import convert_dtype
    acc = convert_dtype(dtype) if dtype is not None else None

    def _cast(v):
        return v.astype(acc) if acc is not None else v

    n_sparse = x._bcoo.indices.shape[1]
    if axis is None:
        out_t = apply_op("sparse_sum_all",
                         lambda v: jnp.sum(_cast(v)), (_values_tensor(x),))
        return out_t
    ax = axis + len(x.shape) if axis < 0 else axis
    if ax >= n_sparse:      # dense (trailing) dim: reduce inside values
        dax = ax - n_sparse + 1
        out_t = apply_op("sparse_sum_dense",
                         lambda v: jnp.sum(_cast(v), axis=dax,
                                           keepdims=keepdim),
                         (_values_tensor(x),))
        new_shape = list(x.shape)
        if keepdim:
            new_shape[ax] = 1
        else:
            new_shape.pop(ax)
        return _from_values_tensor(x, out_t, x._bcoo.indices, new_shape)
    idx = np.asarray(x._bcoo.indices)
    if keepdim:
        new_idx = idx.copy()
        new_idx[:, ax] = 0
        new_shape = list(x.shape)
        new_shape[ax] = 1
    else:
        new_idx = np.delete(idx, ax, axis=1)
        new_shape = list(x.shape)
        new_shape.pop(ax)
    # coalesce duplicates with a segment-sum so grads flow to values
    uniq, inv = np.unique(new_idx, axis=0, return_inverse=True)
    inv = jnp.asarray(inv)
    m = uniq.shape[0]

    def seg(v):
        return jax.ops.segment_sum(_cast(v), inv, num_segments=m)

    out_t = apply_op("sparse_sum", seg, (_values_tensor(x),))
    return _from_values_tensor(x, out_t, jnp.asarray(uniq, jnp.int32),
                               new_shape)


def reshape(x, shape, name=None):
    """Parity: paddle.sparse.reshape — sparse dims remapped through the
    flat index."""
    old_sparse_shape = x.shape[: x._bcoo.indices.shape[1]]
    dense_shape = x.shape[x._bcoo.indices.shape[1]:]
    shape = list(shape)
    if dense_shape:
        if list(shape[len(shape) - len(dense_shape):]) != \
                list(dense_shape):
            raise ValueError("sparse reshape cannot cross the dense dims")
        new_sparse = shape[: len(shape) - len(dense_shape)]
    else:
        new_sparse = shape
    # resolve -1 within the sparse dims only
    n_el = int(np.prod(old_sparse_shape))
    known = int(np.prod([s for s in new_sparse if s != -1]))
    new_sparse = [n_el // known if s == -1 else s for s in new_sparse]
    if int(np.prod(new_sparse)) != n_el:
        raise ValueError(
            f"cannot reshape sparse dims {old_sparse_shape} to "
            f"{new_sparse}")
    idx = np.asarray(x._bcoo.indices)
    flat = np.ravel_multi_index(idx.T, old_sparse_shape)
    new_idx = np.stack(np.unravel_index(flat, new_sparse), axis=1)
    return _from_values_tensor(
        x, _values_tensor(x), jnp.asarray(new_idx, jnp.int32),
        list(new_sparse) + list(dense_shape))


def slice(x, axes, starts, ends, name=None):
    """Parity: paddle.sparse.slice over the sparse dims."""
    from ..core.dispatch import apply_op
    idx = np.asarray(x._bcoo.indices)
    new_shape = list(x.shape)
    keep = np.ones(idx.shape[0], bool)
    shift = np.zeros(idx.shape[1], np.int64)
    for ax, st, en in zip(axes, starts, ends):
        ax = ax + len(x.shape) if ax < 0 else ax
        dim = x.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        if ax >= idx.shape[1]:
            raise NotImplementedError("slice over dense dims")
        keep &= (idx[:, ax] >= st) & (idx[:, ax] < en)
        shift[ax] = st
        new_shape[ax] = en - st
    sel = np.nonzero(keep)[0]
    new_idx = idx[sel] - shift[None, :]
    sel_j = jnp.asarray(sel)
    out_t = apply_op("sparse_slice", lambda v: v[sel_j],
                     (_values_tensor(x),))
    return _from_values_tensor(x, out_t, jnp.asarray(new_idx, jnp.int32),
                               new_shape)


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (parity: sparse mv)."""
    from ..core.dispatch import apply_op
    idx = x._bcoo.indices
    shp = x._bcoo.shape
    v = vec if isinstance(vec, Tensor) else Tensor(vec)

    def fn(vals, dvec):
        return jsparse.BCOO((vals, idx), shape=shp) @ dvec

    return apply_op("sparse_mv", fn, (_values_tensor(x), v))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) — x sparse, input/y dense (parity:
    sparse addmm)."""
    from ..core.dispatch import apply_op
    idx = x._bcoo.indices
    shp = x._bcoo.shape
    inp = input if isinstance(input, Tensor) else Tensor(input)
    dy = y if isinstance(y, Tensor) else Tensor(y)

    def fn(dinp, vals, dv):
        return beta * dinp + alpha * (
            jsparse.BCOO((vals, idx), shape=shp) @ dv)

    return apply_op("sparse_addmm", fn, (inp, _values_tensor(x), dy))


# sparse.nn subpackage (conv/norm/pool/activations) lazily imports names
# from this module, so import it last
from . import nn  # noqa: E402

__all__ += ["asin", "asinh", "atan", "atanh", "acos", "acosh", "sinh",
            "tan", "leaky_relu", "relu6", "isnan", "scale",
            "divide_scalar", "full_like", "sum", "reshape", "slice",
            "mv", "addmm"]


# ---------------------------------------------------------------------------
# round-5 package tail (parity: sparse/__init__ deg2rad/rad2deg/
# is_same_shape/pca_lowrank; sparse/creation.py module path)
# ---------------------------------------------------------------------------
def deg2rad(x, name=None):
    """Parity: paddle.sparse.deg2rad (values-wise unary)."""
    return _value_op_public(x, "sparse_deg2rad",
                            lambda v: v * (jnp.pi / 180.0))


def rad2deg(x, name=None):
    """Parity: paddle.sparse.rad2deg."""
    return _value_op_public(x, "sparse_rad2deg",
                            lambda v: v * (180.0 / jnp.pi))


def _value_op_public(x, name, fn):
    from .nn import functional as _  # noqa: F401 (package init)
    return _value_op(x, name, fn)


def is_same_shape(x, y) -> bool:
    """Parity: paddle.sparse.is_same_shape."""
    return list(x.shape) == list(y.shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Parity: paddle.sparse.pca_lowrank — randomized low-rank PCA of a
    sparse matrix: returns (U, S, V) with X ~ U diag(S) V^T.  The
    randomized range finder (Halko et al.) runs its matmuls through the
    sparse kernel so X never densifies."""
    from ..ops import random as _random
    import jax
    m, n = int(x.shape[0]), int(x.shape[1])
    if q is None:
        q = min(6, m, n)
    if not (0 <= q <= min(m, n)):
        raise ValueError(f"q={q} out of range for shape {x.shape}")
    # Halko-style oversampling: project with extra columns, truncate to q
    q_eff = min(q + 10, m, n)
    # materialize through matmuls only: Y = X @ G  (sparse @ dense)
    key = _random.next_key()
    G = jax.random.normal(key, (n, q_eff), jnp.float32)
    c = None
    if center:
        idx = np.asarray(x._bcoo.indices)
        colsum = np.zeros(n, np.float32)
        np.add.at(colsum, idx[:, 1], np.asarray(x._bcoo.data,
                                                np.float32))
        c = jnp.asarray(colsum / m)          # column means
    Y = matmul(x, Tensor._from_value(G))._value
    if c is not None:
        Y = Y - jnp.outer(jnp.ones(m), c @ G)
    Q, _r = jnp.linalg.qr(Y)
    for _ in range(niter):
        Z = matmul(transpose(x, [1, 0]), Tensor._from_value(Q))._value
        if c is not None:
            Z = Z - jnp.outer(c, jnp.ones(m) @ Q)
        Qz, _r = jnp.linalg.qr(Z)
        Y = matmul(x, Tensor._from_value(Qz))._value
        if c is not None:
            Y = Y - jnp.outer(jnp.ones(m), c @ Qz)
        Q, _r = jnp.linalg.qr(Y)
    B = matmul(transpose(x, [1, 0]), Tensor._from_value(Q))._value
    if c is not None:
        B = B - jnp.outer(c, jnp.ones(m) @ Q)
    Ub, S, Vt = jnp.linalg.svd(B.T, full_matrices=False)
    U = Q @ Ub
    return (Tensor._from_value(U[:, :q]), Tensor._from_value(S[:q]),
            Tensor._from_value(Vt[:q].T))


__all__ += ["deg2rad", "rad2deg", "is_same_shape", "pca_lowrank"]
