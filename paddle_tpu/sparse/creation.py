"""Parity import path: paddle.sparse.creation (__all__ =
[sparse_coo_tensor, sparse_csr_tensor]); implementations in the package
__init__."""
from . import sparse_coo_tensor, sparse_csr_tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor"]
