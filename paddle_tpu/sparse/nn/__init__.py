"""paddle.sparse.nn — sparse layers.

Parity: python/paddle/sparse/nn/ (reference — layer/conv.py Conv3D:239 /
SubmConv3D:509, layer/norm.py BatchNorm:24, layer/pooling.py MaxPool3D:20,
layer/activation.py).  Functional ops in :mod:`.functional` (the
gather-GEMM-scatter rulebook implementation).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ...nn import initializer as I
from . import functional as F
from .functional import conv3d, subm_conv3d, max_pool3d, attention

__all__ = ["Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D", "BatchNorm",
           "SyncBatchNorm", "MaxPool3D", "ReLU", "ReLU6", "LeakyReLU",
           "Softmax", "functional"]
functional = F


class _Conv3D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False,
                 key=None, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NDHWC"):
        super().__init__()
        ks = F._triple(kernel_size)
        self._subm = subm
        self._key = key
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self.weight = self.create_parameter(
            [*ks, in_channels // groups, out_channels], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return conv3d(x, self.weight, self.bias, self._stride,
                      self._padding, self._dilation, self._groups,
                      subm=self._subm, key=self._key,
                      data_format=self._data_format)


class Conv3D(_Conv3D):
    """Parity: paddle.sparse.nn.Conv3D (layer/conv.py:239)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format)


class SubmConv3D(_Conv3D):
    """Parity: paddle.sparse.nn.SubmConv3D (layer/conv.py:509) — output
    sparsity pattern equals the input pattern."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, key=key,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format)


class BatchNorm(Layer):
    """Batch norm over a sparse tensor's stored values, per channel
    (parity: paddle.sparse.nn.BatchNorm, layer/norm.py:24 — the reference
    subclasses BatchNorm1D and applies it to values())."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum, epsilon,
                               weight_attr, bias_attr, data_format="NLC",
                               use_global_stats=use_global_stats)

    def forward(self, x):
        from .. import _values_tensor, _from_values_tensor
        vals = _values_tensor(x)
        out = self._bn(vals.unsqueeze(0)).squeeze(0)
        return _from_values_tensor(x, out, x._bcoo.indices,
                                   x._bcoo.shape)


class MaxPool3D(Layer):
    """Parity: paddle.sparse.nn.MaxPool3D (layer/pooling.py:20)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, return_mask=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        self._ks, self._st, self._pd = kernel_size, stride, padding
        self._data_format = data_format

    def forward(self, x):
        return max_pool3d(x, self._ks, self._st, self._pd,
                          data_format=self._data_format)


class ReLU(Layer):
    def forward(self, x):
        from .. import relu
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from .. import relu6
        return relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from .. import leaky_relu
        return leaky_relu(x, self._slope)


class Softmax(Layer):
    """Softmax over the last sparse axis, grouped by all leading sparse
    coordinates (parity: paddle.sparse.nn.Softmax — only axis=-1 is
    supported, like the reference)."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1 only")

    def forward(self, x):
        from ...core.dispatch import apply_op
        from .. import _values_tensor, _from_values_tensor
        idx = np.asarray(x._bcoo.indices)
        # group key = all sparse coords except the last (the softmax axis)
        lead = idx[:, :-1]
        uniq, rows_np = np.unique(lead, axis=0, return_inverse=True)
        rows = jnp.asarray(rows_np)
        n_rows = uniq.shape[0]

        def compute(data):
            row_max = jnp.full((n_rows,), -jnp.inf,
                               data.dtype).at[rows].max(data)
            e = jnp.exp(data - row_max[rows])
            denom = jnp.zeros((n_rows,), data.dtype).at[rows].add(e)
            return e / denom[rows]

        out_t = apply_op("sparse_softmax", compute, (_values_tensor(x),))
        return _from_values_tensor(x, out_t, x._bcoo.indices,
                                   x._bcoo.shape)

    __call__ = forward


class _Conv2D(Layer):
    """Shared 2-D sparse conv body (lifts onto the 3-D rulebook)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__()
        ks = F._pair(kernel_size)
        self._subm = subm
        self._key = key
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self.weight = self.create_parameter(
            [*ks, in_channels // groups, out_channels], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels], attr=bias_attr, is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        subm=self._subm, key=self._key,
                        data_format=self._data_format)


class Conv2D(_Conv2D):
    """Parity: paddle.sparse.nn.Conv2D (layer/conv.py:570)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         padding_mode=padding_mode,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)


class SubmConv2D(_Conv2D):
    """Parity: paddle.sparse.nn.SubmConv2D — submanifold 2-D conv."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, key=key,
                         padding_mode=padding_mode,
                         weight_attr=weight_attr, bias_attr=bias_attr,
                         data_format=data_format)


class SyncBatchNorm(BatchNorm):
    """Parity: paddle.sparse.nn.SyncBatchNorm — BatchNorm whose batch
    statistics are averaged across the data-parallel group.  Under a
    jitted sharded step GSPMD inserts the cross-replica mean reduction
    automatically; in eager multi-process mode the values-stat moments
    ride an explicit all_reduce."""

    def forward(self, x):
        from ...distributed.env import get_world_size
        if get_world_size() <= 1:
            return super().forward(x)
        from ...core.dispatch import apply_op
        from ...distributed.collective import all_reduce
        from .. import _values_tensor, _from_values_tensor
        from ...core.tensor import Tensor as _T
        vals = _values_tensor(x)
        n = vals._value.shape[0]
        # cross-rank moments of the nnz values (per channel)
        s1 = _T(np.asarray(
            jnp.sum(vals._value, axis=0, dtype=jnp.float32)))
        s2 = _T(np.asarray(
            jnp.sum(jnp.square(vals._value.astype(jnp.float32)), axis=0)))
        cnt = _T(np.float32(n))
        for t in (s1, s2, cnt):
            all_reduce(t)
        mean = s1._value / cnt._value
        var = s2._value / cnt._value - jnp.square(mean)
        bn = self._bn
        eps = bn._epsilon
        w = bn.weight._value if bn.weight is not None else 1.0
        b = bn.bias._value if bn.bias is not None else 0.0

        def fn(v):
            return ((v - mean) * jax.lax.rsqrt(var + eps) * w + b)                 .astype(v.dtype)

        out_t = apply_op("sparse_sync_batch_norm", fn, (vals,))
        return _from_values_tensor(x, out_t, x._bcoo.indices,
                                   x._bcoo.shape)

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Parity: SyncBatchNorm.convert_sync_batchnorm — recursively
        swap BatchNorm sublayers for SyncBatchNorm."""
        if isinstance(layer, BatchNorm) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm.__new__(SyncBatchNorm)
            out.__dict__.update(layer.__dict__)
            return out
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer
