"""Sparse conv/pool/attention functional ops.

Parity: python/paddle/sparse/nn/functional/ (reference — conv.py conv3d/
subm_conv3d over the conv3d_coo kernel with its gather-GEMM-scatter
"rulebook", paddle/phi/kernels/sparse/gpu/conv_kernel.cu; pooling
max_pool3d; transformer.py attention over SparseCsrTensor masks).

TPU-native: the rulebook (which input point feeds which output point for
each kernel offset) is computed host-side in numpy — it is pure integer
coordinate matching, data-independent given the sparsity pattern — and
the differentiable value math (per-offset gather -> (n, Ci) @ (Ci, Co)
GEMM on the MXU -> scatter-add) runs through dispatch so gradients flow
to features, kernel and bias via the tape.

Compile hygiene for training loops where the point cloud changes every
step (the reference amortizes via rulebook/workspace reuse,
paddle/phi/kernels/sparse/gpu/conv_kernel.cu):
- rulebooks are cached keyed on a fingerprint of the coords + geometry,
  so a repeated cloud never re-matches coordinates;
- gather/scatter index lists are PADDED to power-of-two buckets and fed
  to the kernel as runtime arrays (not baked-in constants), so XLA sees
  a stable shape signature across steps and reuses its compiled kernels
  instead of recompiling per batch.  ``compile_stats()`` exposes the
  distinct-signature count the tests assert on."""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...core.dispatch import apply_op

_RULEBOOK_CACHE: "OrderedDict[bytes, tuple]" = OrderedDict()
_RULEBOOK_CACHE_MAX = 64
_KERNEL_SIGS = set()
_STATS = {"rulebook_builds": 0, "rulebook_hits": 0, "kernel_compiles": 0}


def compile_stats() -> dict:
    """Counters: rulebook_builds / rulebook_hits / kernel_compiles (the
    number of distinct padded shape signatures — each is one XLA
    compile; bucket reuse across steps keeps it bounded)."""
    return dict(_STATS)


def clear_compile_stats():
    _RULEBOOK_CACHE.clear()
    _KERNEL_SIGS.clear()
    for k in _STATS:
        _STATS[k] = 0


def _bucket(n: int, base: int = 16) -> int:
    """Next power-of-two >= n (min ``base``): bounds the number of
    distinct padded shapes XLA ever compiles to O(log nnz)."""
    if n <= base:
        return base
    return 1 << (int(n) - 1).bit_length()


def _track_sig(*sig) -> None:
    if sig not in _KERNEL_SIGS:
        _KERNEL_SIGS.add(sig)
        _STATS["kernel_compiles"] += 1


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _offsets(kernel_dhw):
    kd, kh, kw = kernel_dhw
    return [(a, b, c) for a in range(kd) for b in range(kh)
            for c in range(kw)]


def _lin(coords, dims):
    """coords (n, 4) [batch, d, h, w] -> int64 scalar key."""
    key = coords[:, 0].astype(np.int64)
    for i, s in enumerate(dims):
        key = key * s + coords[:, i + 1]
    return key


def _build_rulebook(in_coords: np.ndarray, spatial, kernel_dhw, strides,
                    paddings, dilations, subm: bool):
    """The conv rulebook: output coords + per-offset (gather, scatter)
    index pairs (reference: Conv3dCooKernel's rulebook/counter outputs)."""
    kd, kh, kw = kernel_dhw
    st = np.asarray(strides)
    pd = np.asarray(paddings)
    dl = np.asarray(dilations)
    ksz = np.asarray(kernel_dhw)
    out_spatial = tuple(
        (np.asarray(spatial) + 2 * pd - dl * (ksz - 1) - 1) // st + 1)

    if in_coords.shape[0] == 0:
        # empty cloud: empty output, no pairs (the searchsorted block
        # below would index into empty sorted_keys)
        empty = np.zeros(0, np.int64)
        return (np.zeros((0, 4), np.int64),
                tuple(spatial) if subm else out_spatial,
                [(empty, empty) for _ in _offsets(kernel_dhw)])

    if subm:
        if tuple(st) != (1, 1, 1):
            raise ValueError("submanifold conv requires stride 1")
        out_coords = in_coords
        out_spatial = tuple(spatial)
    else:
        cands = []
        for off in _offsets(kernel_dhw):
            c = in_coords[:, 1:4] + pd - dl * np.asarray(off)
            ok = np.all((c % st == 0) & (c >= 0), axis=1)
            o = c[ok] // st
            ok2 = np.all(o < np.asarray(out_spatial), axis=1)
            cands.append(np.concatenate(
                [in_coords[ok][ok2][:, :1], o[ok2]], axis=1))
        allc = np.concatenate(cands, axis=0) if cands else \
            np.zeros((0, 4), np.int64)
        out_coords = np.unique(allc, axis=0)

    in_keys = _lin(in_coords, spatial)
    order = np.argsort(in_keys)
    sorted_keys = in_keys[order]

    pairs = []
    for off in _offsets(kernel_dhw):
        tgt = out_coords[:, 1:4] * st - pd + dl * np.asarray(off)
        valid = np.all((tgt >= 0) & (tgt < np.asarray(spatial)), axis=1)
        keys = _lin(np.concatenate([out_coords[:, :1], tgt], axis=1),
                    spatial)
        pos = np.searchsorted(sorted_keys, keys)
        pos = np.clip(pos, 0, len(sorted_keys) - 1)
        found = valid & (sorted_keys[pos] == keys)
        j_out = np.nonzero(found)[0]
        i_in = order[pos[found]]
        pairs.append((i_in.astype(np.int32), j_out.astype(np.int32)))
    return out_coords.astype(np.int64), out_spatial, pairs


def _cached_rulebook(in_coords, spatial, kernel_dhw, strides, paddings,
                     dilations, subm):
    """LRU rulebook cache + bucket padding.

    Returns ``(out_coords, out_spatial, m, m_pad, padded_pairs)`` where
    each padded pair is (gather, scatter) int32 device arrays of
    power-of-two length; padding gathers row 0 and scatters to the
    sentinel row ``m_pad`` (dropped by the kernel's static slice)."""
    h = hashlib.sha1(in_coords.tobytes())
    h.update(np.asarray(
        [in_coords.shape[0], *spatial, *kernel_dhw, *strides, *paddings,
         *dilations, int(subm)], np.int64).tobytes())
    key = h.digest()
    ent = _RULEBOOK_CACHE.get(key)
    if ent is not None:
        _RULEBOOK_CACHE.move_to_end(key)
        _STATS["rulebook_hits"] += 1
        return ent
    _STATS["rulebook_builds"] += 1
    out_coords, out_spatial, pairs = _build_rulebook(
        in_coords, spatial, kernel_dhw, strides, paddings, dilations,
        subm)
    m = out_coords.shape[0]
    m_pad = _bucket(max(m, 1))
    # ONE padded length for every offset (the bucketed max): the shape
    # signature is then a single number, so clouds of similar density
    # share one compiled kernel even when per-offset counts differ
    p = _bucket(max([1] + [len(gi) for gi, _ in pairs]))
    padded = []
    for gi, so in pairs:
        gi_p = np.zeros(p, np.int32)
        gi_p[: len(gi)] = gi
        so_p = np.full(p, m_pad, np.int32)
        so_p[: len(so)] = so
        padded.append((jnp.asarray(gi_p), jnp.asarray(so_p)))
    ent = (out_coords, out_spatial, m, m_pad, padded)
    _RULEBOOK_CACHE[key] = ent
    while len(_RULEBOOK_CACHE) > _RULEBOOK_CACHE_MAX:
        _RULEBOOK_CACHE.popitem(last=False)
    return ent


def _pad_values(vals_t: Tensor, nnz: int):
    """Pad values rows to the nnz bucket (tape op: grads slice back)."""
    nnz_pad = _bucket(nnz)
    if nnz_pad == nnz:
        return vals_t, nnz_pad
    out = apply_op(
        "sparse_pad_values",
        lambda v: jnp.pad(v, ((0, nnz_pad - nnz), (0, 0))), (vals_t,))
    return out, nnz_pad


def _sp_parts(x):
    """(values Tensor, indices np, batch, spatial, channels)."""
    from .. import _values_tensor
    idx = np.asarray(x._bcoo.indices, np.int64)
    if idx.shape[1] != 4 or x._bcoo.data.ndim != 2:
        raise ValueError(
            "sparse conv/pool expect an NDHWC tensor with 4 sparse dims "
            "(batch, d, h, w) and a DENSE channel dim — build it as "
            "sparse_coo_tensor(indices[4, nnz], values[nnz, C], shape); "
            f"got {idx.shape[1]} sparse dims, values ndim "
            f"{x._bcoo.data.ndim}")
    shape = x.shape
    return (_values_tensor(x), idx, shape[0], tuple(shape[1:4]), shape[4])


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           subm=False, key=None, data_format="NDHWC", name=None):
    """Sparse 3-D convolution over an NDHWC SparseCooTensor (parity:
    paddle.sparse.nn.functional.conv3d / subm_conv3d; sparse_ops.yaml
    conv3d)."""
    from .. import _from_values_tensor
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d supports NDHWC only")
    if groups != 1:
        raise NotImplementedError("sparse conv3d: groups must be 1")
    vals_t, idx, batch, spatial, cin = _sp_parts(x)
    w = weight if isinstance(weight, Tensor) else Tensor(weight)
    kd, kh, kw = (int(s) for s in w.shape[:3])
    out_coords, out_spatial, m, m_pad, pairs = _cached_rulebook(
        idx, spatial, (kd, kh, kw), _triple(stride), _triple(padding),
        _triple(dilation), subm)
    cout = int(w.shape[-1])
    out_shape = [batch, *out_spatial, cout]
    nnz = idx.shape[0]
    if nnz == 0 or m == 0:
        out_t = apply_op(
            "sparse_conv3d",
            lambda f, wk, *b: jnp.zeros((m, cout), f.dtype),
            [vals_t, w] + ([bias] if bias is not None else []))
        return _from_values_tensor(x, out_t,
                                   jnp.asarray(out_coords, jnp.int32),
                                   out_shape)

    vals_p, nnz_pad = _pad_values(vals_t, nnz)
    K = kd * kh * kw
    _track_sig("conv3d", nnz_pad, m_pad, cin, cout,
               tuple(int(gi.shape[0]) for gi, _ in pairs),
               str(vals_t._value.dtype), bias is not None)
    flat_idx = [a for p in pairs for a in p]
    n_extra = 1 if bias is not None else 0

    def compute(feats, wk, *rest):
        b = rest[:n_extra]
        idxs = rest[n_extra:]
        wk2 = wk.reshape(K, cin, cout)
        # sentinel row m_pad absorbs padded pairs; dropped by the slice
        out = jnp.zeros((m_pad + 1, cout), feats.dtype)
        for k in range(K):
            gi, so = idxs[2 * k], idxs[2 * k + 1]
            out = out.at[so].add(feats[gi] @ wk2[k])
        out = out[:m_pad]
        if b:
            out = out + b[0]
        return out

    tensor_args = [vals_p, w] + ([bias] if bias is not None else []) \
        + flat_idx
    out_t = apply_op("sparse_conv3d", compute, tensor_args)
    if m != m_pad:
        out_t = out_t[:m]
    return _from_values_tensor(x, out_t,
                               jnp.asarray(out_coords, jnp.int32),
                               out_shape)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, key=None, data_format="NDHWC", name=None):
    return conv3d(x, weight, bias, stride, padding, dilation, groups,
                  subm=True, key=key, data_format=data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse 3-D max pooling over existing points (parity:
    paddle.sparse.nn.functional.max_pool3d; sparse_ops.yaml maxpool)."""
    from .. import _from_values_tensor
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d supports NDHWC only")
    ks = _triple(kernel_size)
    st = _triple(stride if stride is not None else kernel_size)
    vals_t, idx, batch, spatial, ch = _sp_parts(x)
    out_coords, out_spatial, m, m_pad, pairs = _cached_rulebook(
        idx, spatial, ks, st, _triple(padding), (1, 1, 1), subm=False)
    out_shape = [batch, *out_spatial, ch]
    nnz = idx.shape[0]
    if nnz == 0 or m == 0:
        out_t = apply_op("sparse_maxpool",
                         lambda f: jnp.zeros((m, ch), f.dtype), (vals_t,))
        return _from_values_tensor(x, out_t,
                                   jnp.asarray(out_coords, jnp.int32),
                                   out_shape)

    vals_p, nnz_pad = _pad_values(vals_t, nnz)
    _track_sig("maxpool", nnz_pad, m_pad, ch,
               tuple(int(gi.shape[0]) for gi, _ in pairs),
               str(vals_t._value.dtype))

    def compute(feats, *idxs):
        out = jnp.full((m_pad + 1, ch), -jnp.inf, feats.dtype)
        for k in range(len(idxs) // 2):
            gi, so = idxs[2 * k], idxs[2 * k + 1]
            out = out.at[so].max(feats[gi])
        # every REAL out coord has >=1 contributor by construction;
        # rows m..m_pad and the sentinel are dropped by the slices
        return out[:m_pad]

    flat_idx = [a for p in pairs for a in p]
    out_t = apply_op("sparse_maxpool", compute, [vals_p] + flat_idx)
    if m != m_pad:
        out_t = out_t[:m]
    return _from_values_tensor(x, out_t,
                               jnp.asarray(out_coords, jnp.int32),
                               out_shape)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-pattern attention: softmax((QK^T)/sqrt(d) over the mask's
    nonzero pattern) V (parity: paddle.sparse.nn.functional.attention,
    sparse_ops.yaml fused_attention over a SparseCsrTensor mask).

    query/key/value: dense (B, H, S, D) Tensors.  sparse_mask's pattern
    selects which scores exist: a 2-D (S, S) mask is shared by every
    batch/head; a 3-D (B*H, S, S) mask (the reference's CSR layout)
    selects per batch-head.  ``key_padding_mask`` (B, S) and
    ``attn_mask`` (S, S) are ADDED to the scores like the reference
    (use -inf/large-negative to mask out)."""
    q = query if isinstance(query, Tensor) else Tensor(query)
    k = key if isinstance(key, Tensor) else Tensor(key)
    v = value if isinstance(value, Tensor) else Tensor(value)
    idx = np.asarray(sparse_mask._bcoo.indices, np.int64)
    rows = jnp.asarray(idx[:, -2])
    cols = jnp.asarray(idx[:, -1])
    per_bh = idx.shape[1] >= 3
    bidx = jnp.asarray(idx[:, 0]) if per_bh else None
    B, H, S, _ = q.shape
    kpm = None
    if key_padding_mask is not None:
        kpm = key_padding_mask._value if isinstance(
            key_padding_mask, Tensor) else jnp.asarray(key_padding_mask)
    amask = None
    if attn_mask is not None:
        amask = attn_mask._value if isinstance(attn_mask, Tensor) \
            else jnp.asarray(attn_mask)

    def compute(qv, kv, vv):
        d = qv.shape[-1]
        scale = jnp.sqrt(jnp.asarray(d, jnp.float32)).astype(qv.dtype)
        qf = qv.reshape(B * H, S, d)
        kf = kv.reshape(B * H, S, d)
        vf = vv.reshape(B * H, S, d)
        if per_bh:
            # per-(batch*head) pattern: scores per nnz, segmented rows
            qs = qf[bidx, rows]
            ks = kf[bidx, cols]
            scores = (qs * ks).sum(-1) / scale          # (nnz,)
            if kpm is not None:
                scores = scores + kpm.reshape(B, S)[bidx // H, cols]
            if amask is not None:
                scores = scores + amask[rows, cols]
            seg = bidx * S + rows
            nseg = B * H * S
            smax = jnp.full((nseg,), -jnp.inf,
                            scores.dtype).at[seg].max(scores)
            e = jnp.exp(scores - smax[seg])
            den = jnp.zeros((nseg,), scores.dtype).at[seg].add(e)
            p = e / den[seg]
            out = jnp.zeros_like(qf).at[bidx, rows].add(
                p[:, None] * vf[bidx, cols])
            return out.reshape(qv.shape)
        # shared (S, S) pattern: vectorized over batch*head
        qs = qf[:, rows]                                 # (BH, nnz, d)
        ks = kf[:, cols]
        scores = (qs * ks).sum(-1) / scale               # (BH, nnz)
        if kpm is not None:
            pad = jnp.repeat(kpm.reshape(B, S), H, axis=0)  # (BH, S)
            scores = scores + pad[:, cols]
        if amask is not None:
            scores = scores + amask[rows, cols]
        smax = jnp.full((B * H, S), -jnp.inf,
                        scores.dtype).at[:, rows].max(scores)
        e = jnp.exp(scores - smax[:, rows])
        den = jnp.zeros((B * H, S), scores.dtype).at[:, rows].add(e)
        p = e / den[:, rows]
        out = jnp.zeros_like(qf).at[:, rows].add(
            p[..., None] * vf[:, cols])
        return out.reshape(qv.shape)

    return apply_op("sparse_fused_attention", compute, (q, k, v))


# ---------------------------------------------------------------------------
# 2-D variants (parity: python/paddle/sparse/nn/functional/conv.py conv2d/
# subm_conv2d): lifted onto the 3-D rulebook with a unit depth dim, so
# they share the cache/bucketing machinery above.
# ---------------------------------------------------------------------------
def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 2


def _lift_2d(x):
    """NHWC sparse (3 sparse dims) -> NDHWC with D=1."""
    from .. import SparseCooTensor, _values_tensor
    import jax.numpy as jnp
    idx = np.asarray(x._bcoo.indices, np.int64)     # [nnz, 3] (b, h, w)
    if idx.shape[1] != 3:
        raise ValueError(
            "sparse conv2d expects an NHWC tensor with 3 sparse dims "
            "(batch, h, w) and a dense channel dim")
    lifted_idx = np.concatenate(
        [idx[:, :1], np.zeros((idx.shape[0], 1), np.int64), idx[:, 1:]],
        axis=1)
    shape = x.shape
    from jax.experimental import sparse as jsparse
    lifted = SparseCooTensor(jsparse.BCOO(
        (x._bcoo.data, jnp.asarray(lifted_idx, jnp.int32)),
        shape=(shape[0], 1, shape[1], shape[2], shape[3])))
    t = getattr(x, "_values_t", None)
    if t is not None:
        lifted._values_t = t
    return lifted


def _drop_depth(y):
    from .. import SparseCooTensor, _from_values_tensor, _values_tensor
    import jax.numpy as jnp
    idx = np.asarray(y._bcoo.indices, np.int64)     # [nnz, 4]
    flat = np.concatenate([idx[:, :1], idx[:, 2:]], axis=1)
    s = y.shape
    return _from_values_tensor(y, _values_tensor(y),
                               jnp.asarray(flat, jnp.int32),
                               (s[0], s[2], s[3], s[4]))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, subm=False, key=None, data_format="NHWC", name=None):
    """Parity: paddle.sparse.nn.functional.conv2d (weight [kh, kw, ci,
    co])."""
    if data_format != "NHWC":
        raise ValueError("sparse conv2d supports NHWC only")
    w = weight if isinstance(weight, Tensor) else Tensor(weight)
    import jax.numpy as jnp
    w3 = Tensor._from_value(w._value[None])   # [1, kh, kw, ci, co]
    w3.stop_gradient = w.stop_gradient
    if not w.stop_gradient:
        from ...core.dispatch import apply_op
        w3 = apply_op("sparse_conv2d_lift_w",
                      lambda v: v[None], (w,))
    st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
    out = conv3d(_lift_2d(x), w3, bias, (1,) + st, (0,) + pd,
                 (1,) + dl, groups, subm=subm, key=key)
    return _drop_depth(out)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, key=None, data_format="NHWC", name=None):
    return conv2d(x, weight, bias, stride, padding, dilation, groups,
                  subm=True, key=key, data_format=data_format)


# activation re-exports (parity: sparse/nn/functional/__init__.py lists
# relu/relu6/leaky_relu/softmax alongside the conv family)
def relu(x, name=None):
    from .. import relu as _impl
    return _impl(x, name)


def relu6(x, name=None):
    from .. import relu6 as _impl
    return _impl(x, name)


def leaky_relu(x, negative_slope=0.01, name=None):
    from .. import leaky_relu as _impl
    return _impl(x, negative_slope, name)


def softmax(x, axis=-1, name=None):
    from . import Softmax
    return Softmax(axis)(x)
