"""Runtime flag registry.

Capability parity with the reference's FLAGS system
(reference: paddle/phi/core/flags.cc — 126 PHI_DEFINE_EXPORTED_* definitions;
paddle/utils/flags.h:24 gflags wrapper with a self-contained native fallback).

Flags are process-global knobs, settable three ways (same precedence as the
reference): definition default < environment variable ``FLAGS_<name>`` <
explicit ``set_flags``.  A native C++ registry can be slotted behind this
module later; the Python registry is authoritative for now.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_lock = threading.RLock()


@dataclass
class _FlagDef:
    name: str
    default: Any
    type: type
    help: str
    on_change: Optional[Callable[[Any], None]] = None
    value: Any = None


_REGISTRY: Dict[str, _FlagDef] = {}


def _parse(raw: str, ty: type):
    if ty is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    return ty(raw)


def define_flag(name: str, default, help: str = "", type: type | None = None,
                on_change=None):
    """Define a flag. Environment ``FLAGS_<name>`` overrides the default."""
    ty = type if type is not None else default.__class__
    with _lock:
        env = os.environ.get("FLAGS_" + name)
        value = _parse(env, ty) if env is not None else default
        _REGISTRY[name] = _FlagDef(name, default, ty, help, on_change, value)
    return value


def _canon(name: str) -> str:
    # the reference spells flags "FLAGS_<name>" at the set_flags/get_flags
    # surface (python/paddle/base/framework.py set_flags); the registry
    # stores bare names — accept both
    return name[6:] if name.startswith("FLAGS_") else name


def get_flags(flags=None) -> Dict[str, Any]:
    """Query flag values. ``flags`` may be a name, list of names, or None (all)."""
    with _lock:
        if flags is None:
            return {k: d.value for k, d in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for k in flags:
            c = _canon(k)
            if c not in _REGISTRY:
                raise ValueError(f"Flag {k!r} is not defined")
            out[k] = _REGISTRY[c].value
        return out


def get_flag(name: str):
    return get_flags([name])[name]


def set_flags(flags: Dict[str, Any]):
    """Set flag values (same surface as paddle.set_flags)."""
    with _lock:
        for k, v in flags.items():
            k = _canon(k)
            if k not in _REGISTRY:
                raise ValueError(f"Flag {k!r} is not defined")
            d = _REGISTRY[k]
            if isinstance(v, str) and d.type is not str:
                v = _parse(v, d.type)
            d.value = d.type(v) if not isinstance(v, d.type) else v
            if d.on_change is not None:
                d.on_change(d.value)


# ---------------------------------------------------------------------------
# Core flag definitions (subset of reference paddle/phi/core/flags.cc that is
# meaningful on TPU; more are defined next to their subsystems).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False,
            "Check outputs of every op for NaN/Inf (numerical sanitizer; "
            "reference: paddle/phi/core/flags.cc:62)")
define_flag("check_nan_inf_level", 0,
            "0: error on nan/inf; >0: warn levels "
            "(reference: paddle/phi/core/flags.cc:88)")
define_flag("benchmark", False, "Sync after every op for timing")
define_flag("eager_compile_ops", True,
            "Route eager op dispatch through the jit executable cache "
            "(the TPU analog of the reference's per-op kernel dispatch)")
define_flag("use_pallas_kernels", True,
            "Use hand-written Pallas kernels for fused ops when on TPU")
define_flag("allocator_strategy", "auto_growth",
            "Kept for API parity; PJRT owns device memory on TPU "
            "(reference: paddle/fluid/memory/allocation/allocator_strategy.cc:31)")
define_flag("tpu_deterministic", False, "Request deterministic XLA reductions")
define_flag("log_level", 0, "Verbose logging level (GLOG_v analog)")
