"""The eager Tensor.

Capability parity with the reference's paddle::Tensor + eager AutogradMeta
(reference: paddle/phi/api/include/tensor.h:82, autograd meta
paddle/fluid/eager/autograd_meta.h, Python surface
paddle/fluid/pybind/eager_method.cc / eager_properties.cc).

TPU-native design: a Tensor owns a ``jax.Array`` (a PJRT buffer — possibly
sharded across a device mesh, which is how DistTensor parity is achieved; see
paddle_tpu.distributed) plus autograd metadata (tape node + accumulated
``.grad``).  Most math methods are attached from the op library at import
time (the analog of the generated Python method table in
paddle/fluid/pybind/eager_op_function.cc).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import dtypes as _dt
from .device import get_place
from ..autograd import tape as _tape


def _default_cast(data):
    """Numpy conversion with paddle-style defaults: python floats -> default
    float dtype (float32), python ints -> int64 (x64 is enabled at package
    import so int64 survives the jnp conversion)."""
    arr = np.asarray(data)
    if arr.dtype == np.float64 and not isinstance(data,
                                                  (np.ndarray, np.generic)):
        # python floats / float lists take the configured default;
        # an explicit np.float64 array or scalar is honored (x64 is on).
        arr = arr.astype(_dt.get_default_dtype())
    return arr


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_grad_node",
                 "_out_index", "name", "persistable", "_hooks",
                 "trainable", "__weakref__", "__dict__")

    _next_id = [0]

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if data is None:
            value = jnp.zeros((), _dt.get_default_dtype())
        elif isinstance(data, Tensor):
            value = data._value
        elif isinstance(data, jax.Array) or isinstance(data, jax.core.Tracer):
            value = data
        else:
            value = jnp.asarray(_default_cast(data))
        if dtype is not None:
            d = _dt.convert_dtype(dtype)
            if value.dtype != d:
                value = value.astype(d)
        if place is not None and not isinstance(value, jax.core.Tracer):
            value = jax.device_put(value, place.jax_device)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None           # raw jax.Array accumulator
        self._grad_node = None      # producing GradNode
        self._out_index = 0
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks = []
        if name is None:
            Tensor._next_id[0] += 1
            name = f"generated_tensor_{Tensor._next_id[0]}"
        self.name = name

    # -- construction helpers ------------------------------------------------
    @classmethod
    def _from_value(cls, value) -> "Tensor":
        t = cls.__new__(cls)
        t._value = value
        t.stop_gradient = True
        t._grad = None
        t._grad_node = None
        t._out_index = 0
        t.persistable = False
        t.trainable = False
        t._hooks = []
        Tensor._next_id[0] += 1
        t.name = f"generated_tensor_{Tensor._next_id[0]}"
        return t

    # -- basic metadata ------------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        return get_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    def numel(self):
        return self.size

    def element_size(self):
        return np.dtype(self._value.dtype).itemsize

    # -- value access --------------------------------------------------------
    def _notify_sot_materialize(self, what: str):
        """Safety net for jit/sot recording: host materialization of a
        tensor value (bool/int/item/numpy/print) makes the recorded trace
        value-dependent, so the recorder marks the frame eager-only."""
        from .dispatch import _sot_recorder
        rec = _sot_recorder[0]
        if rec is not None:
            rec.poison(f"tensor value materialized on host via {what}")

    def numpy(self) -> np.ndarray:
        self._notify_sot_materialize("numpy()")
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    # -- autograd ------------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        if self._grad is None:
            return None
        return Tensor._from_value(self._grad)

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad = None
        else:
            self._grad = value._value if isinstance(value, Tensor) \
                else jnp.asarray(value)

    def _accumulate_grad(self, g):
        # hooks apply to each incoming contribution (parity: Tensor hooks in
        # GradNodeAccumulation, paddle/fluid/eager/accumulation/)
        for h in self._hooks:
            out = h(Tensor._from_value(g))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
        if self._grad is None:
            self._grad = g
        else:
            self._grad = self._grad + g

    def backward(self, grad_tensor=None, retain_graph=False):
        """Reverse-mode from this tensor (parity: Tensor.backward →
        egr::Backward, paddle/fluid/pybind/eager_functions.cc:1363)."""
        _tape.run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = jnp.zeros_like(self._grad)
        else:
            self._grad = None

    def zero_grad(self):
        self.clear_grad()

    def register_hook(self, hook):
        """Hook on gradient accumulation for leaf tensors, or on the tape node
        cotangent for non-leaves (parity: Tensor.register_hook)."""
        if self._grad_node is not None:
            idx = self._out_index

            def node_hook(cots, _idx=idx, _hook=hook):
                cots = list(cots)
                res = _hook(Tensor._from_value(cots[_idx]))
                if res is not None:
                    cots[_idx] = res._value if isinstance(res, Tensor) else res
                return tuple(cots)

            self._grad_node._hooks.append(node_hook)
        else:
            self._hooks.append(hook)
        return hook

    def detach(self) -> "Tensor":
        t = Tensor._from_value(self._value)
        t.stop_gradient = True
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from ..ops import creation  # late import
        from .dispatch import apply_op
        return apply_op("clone", lambda x: x + 0, (self,))

    # -- dtype / shape sugar (heavy math methods are attached by the op lib) -
    def astype(self, dtype) -> "Tensor":
        from .dispatch import apply_op
        d = _dt.convert_dtype(dtype)
        return apply_op("cast", lambda x: x.astype(d), (self,))

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def cpu(self):
        return self

    def tpu(self):
        return self

    def cuda(self, *a, **k):  # compatibility shim
        return self

    def pin_memory(self):
        return self

    # -- in-place rebind with tape continuity --------------------------------
    def _inplace_assign(self, out: "Tensor"):
        """Rebind this tensor to ``out``'s value/node (paddle inplace-op
        semantics with version-counter-style tape continuity).

        ``out``'s GradNode may hold *self* as an input edge; replace it with a
        shadow tensor frozen at the pre-assignment autograd state so the tape
        has no self-loop."""
        node = out._grad_node
        if node is not None:
            shadow = None
            for i, t in enumerate(node.inputs):
                if t is self:
                    if shadow is None:
                        shadow = Tensor._from_value(self._value)
                        shadow._grad_node = self._grad_node
                        shadow._out_index = self._out_index
                        shadow.stop_gradient = self.stop_gradient
                        shadow._hooks = self._hooks
                        if self._grad_node is None and not self.stop_gradient:
                            # leaf: grads of the pre-assignment value still
                            # accumulate on this tensor's .grad
                            shadow._accumulate_grad = \
                                self._accumulate_grad  # type: ignore
                    node.inputs[i] = shadow
        self._value = out._value
        self._grad_node = node
        self._out_index = out._out_index
        if not out.stop_gradient:
            self.stop_gradient = False
        return self

    # -- in-place value update (used by optimizers / load) -------------------
    def set_value(self, value):
        v = value._value if isinstance(value, Tensor) else \
            jnp.asarray(_default_cast(value))
        if tuple(v.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {v.shape} vs {self._value.shape}")
        if v.dtype != self._value.dtype:
            v = v.astype(self._value.dtype)
        self._value = v
        return self

    def get_tensor(self):
        return self

    def copy_(self, other, *a):
        return self.set_value(other)

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    # -- distributed metadata (DistTensor parity; set by shard_tensor) -------
    @property
    def process_mesh(self):
        return getattr(self, "_process_mesh", None)

    @property
    def placements(self):
        return getattr(self, "_placements", None)

    def is_dist(self) -> bool:
        return getattr(self, "_process_mesh", None) is not None

    # -- printing ------------------------------------------------------------
    def __repr__(self):
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, **_print_options())
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}, "
                f"stop_gradient={self.stop_gradient},\n       {body})")

    __str__ = __repr__

    # -- python protocol -----------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        self._notify_sot_materialize("bool()")
        return bool(self._value)

    def __int__(self):
        self._notify_sot_materialize("int()")
        return int(self._value)

    def __float__(self):
        self._notify_sot_materialize("float()")
        return float(self._value)

    def __index__(self):
        self._notify_sot_materialize("__index__")
        return int(self._value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return str(self)


# global print options (parity: python/paddle/tensor/to_string.py
# set_printoptions — precision/threshold/edgeitems/linewidth/sci_mode)
_PRINT_OPTS = {"precision": 6, "threshold": 40, "edgeitems": 3,
               "linewidth": 75, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions parity."""
    for k, v in (("precision", precision), ("threshold", threshold),
                 ("edgeitems", edgeitems), ("sci_mode", sci_mode),
                 ("linewidth", linewidth)):
        if v is not None:
            _PRINT_OPTS[k] = v


def _print_options():
    opts = dict(precision=_PRINT_OPTS["precision"],
                threshold=_PRINT_OPTS["threshold"],
                edgeitems=_PRINT_OPTS["edgeitems"],
                max_line_width=_PRINT_OPTS["linewidth"])
    if _PRINT_OPTS["sci_mode"] is not None:
        opts["formatter"] = {"float_kind":
                             (lambda x: f"%.{_PRINT_OPTS['precision']}e" % x)
                             if _PRINT_OPTS["sci_mode"] else
                             (lambda x: f"%.{_PRINT_OPTS['precision']}f" % x)}
    return opts


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


# -- torch/paddle convenience methods with no jax.Array analog ---------------
def _t_ndimension(self):
    return self.ndim


def _t_contiguous(self):
    """jax arrays are always dense/contiguous; identity for parity."""
    return self


def _t_is_contiguous(self):
    return True


def _t_apply_(self, func):
    """Parity: Tensor.apply_ (python/paddle/tensor/manipulation.py) —
    apply a python callable to the tensor in place (callable receives
    and returns a Tensor/array)."""
    if not self.stop_gradient:
        raise RuntimeError(
            "apply_ cannot be used on a tensor that requires grad")
    out = func(self)
    self._value = out._value if isinstance(out, Tensor) \
        else jnp.asarray(out)
    return self


def _t_apply(self, func):
    if not self.stop_gradient:
        raise RuntimeError(
            "apply cannot be used on a tensor that requires grad (the "
            "callable runs outside the autograd tape)")
    out = func(self)
    return out if isinstance(out, Tensor) else Tensor(out)


Tensor.ndimension = _t_ndimension
Tensor.contiguous = _t_contiguous
Tensor.is_contiguous = _t_is_contiguous
Tensor.apply_ = _t_apply_
Tensor.apply = _t_apply
