"""Eager op dispatch.

Capability parity with the reference's eager dispatch chain
(reference: generated <op>_ad_func in dygraph_functions.cc from
paddle/fluid/eager/auto_code_generator/generator/eager_gen.py →
paddle::experimental::<op> in paddle/phi/api/lib/api.cc →
KernelFactory::SelectKernelOrThrowError paddle/phi/core/kernel_factory.h:324).

TPU-native design: there is no per-backend kernel registry to search — every
op is a pure JAX function lowered to XLA.  ``apply_op`` is the single choke
point that (1) applies AMP auto-cast (analog of
paddle/fluid/eager/amp_utils.h), (2) computes the forward — capturing the VJP
on the same pass when grads are required (replacing the generated GradNode
classes), (3) wraps outputs and records the tape node, (4) optionally checks
NaN/Inf (analog of paddle/fluid/eager/nan_inf_utils.h).

Inside a trace (jax.jit / to_static / value_and_grad) the tape is skipped and
ops execute as plain traced JAX calls, so whole training steps compile into a
single XLA module — the dispatch cache IS jit's executable cache.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import dtypes as _dt
from .flags import get_flag
from ..autograd import tape as _tape

Array = jax.Array

# ---------------------------------------------------------------------------
# AMP hook (filled in by paddle_tpu.amp to avoid an import cycle)
# ---------------------------------------------------------------------------
_amp_state = {"enabled": False, "dtype": None, "level": "O1",
              "white": frozenset(), "black": frozenset()}

# set to the profiler's record callback while a Profiler is RECORDing;
# None otherwise so the off path costs one comparison
_op_profile_hook = [None]

# amp.debugging per-op hook (tensor checker / operator stats); None when
# no debugging tool is active (paddle_tpu/amp/debugging.py)
_amp_debug_hook = [None]

# set to the active SOT StatementIR recorder while jit/sot is tracing a
# frame (reference analog: the StatementIR builder fed by the eval-frame
# hook, python/paddle/jit/sot/symbolic/statement_ir.py); None otherwise
_sot_recorder = [None]


def _is_tracer(v) -> bool:
    return isinstance(v, jax.core.Tracer)


def _check_nan_inf(name: str, vals: Sequence[Array]):
    for v in vals:
        if isinstance(v, Array) and not _is_tracer(v) \
                and jnp.issubdtype(v.dtype, jnp.inexact):
            if bool(jnp.any(~jnp.isfinite(v))):
                msg = f"Operator {name} output contains NaN/Inf"
                if get_flag("check_nan_inf_level") == 0:
                    raise FloatingPointError(msg)
                print("WARNING:", msg)


def amp_policy(name: str, level: str, target, white, black):
    """The O1/O2 white/black-list cast decision (reference:
    python/paddle/amp/amp_lists.py:30,105 and eager_amp_auto_cast.h) —
    single implementation shared by eager dispatch and the static-graph
    AMP retargeting pass."""
    base = name.split("::")[0]
    if base in black:
        return jnp.float32
    if base in white or level == "O2":
        return target
    return None


def _amp_cast_dtype(name: str):
    """Cast target for the active eager auto_cast scope, or None."""
    st = _amp_state
    if not st["enabled"]:
        return None
    return amp_policy(name, st["level"], st["dtype"], st["white"],
                      st["black"])


def _amp_cast(v, cast_to):
    if cast_to is not None and isinstance(v, (Array, jax.core.Tracer)) \
            and jnp.issubdtype(v.dtype, jnp.floating) \
            and v.dtype != cast_to and v.dtype != jnp.float64:
        return v.astype(cast_to)
    return v


def _harmonize_devices(vals: List[Any]) -> List[Any]:
    """When one operand lives on a multi-device mesh and another on a single
    device, replicate the single-device operand onto the mesh (the eager
    analog of the reference's data-transform copy-in,
    paddle/phi/api/lib/data_transform.cc)."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = None
    for v in vals:
        if isinstance(v, Array) and not _is_tracer(v) \
                and isinstance(v.sharding, NamedSharding) \
                and v.sharding.mesh.devices.size > 1:
            mesh = v.sharding.mesh
            break
    if mesh is None:
        return vals
    out = []
    replicated = NamedSharding(mesh, PartitionSpec())
    for v in vals:
        if isinstance(v, Array) and not _is_tracer(v) \
                and len(v.devices()) == 1:
            v = jax.device_put(v, replicated)
        out.append(v)
    return out


def apply_op(name: str, fn: Callable, tensor_args: Sequence,
             kwargs: Optional[Dict[str, Any]] = None,
             multi_output: bool = False):
    """Execute op ``fn(*values, **kwargs)`` over Tensor/array ``tensor_args``.

    ``fn`` must be a pure jax-traceable function.  Non-Tensor entries in
    ``tensor_args`` are passed through untouched (they are non-differentiable
    leaves such as python scalars).  Returns Tensor or tuple of Tensors.
    """
    prof = _op_profile_hook[0]
    if prof is not None:
        import time as _time
        t0 = _time.perf_counter()
        out = _apply_op_inner(name, fn, tensor_args, kwargs, multi_output)
        prof(name, t0, _time.perf_counter(), "Operator")
        return out
    return _apply_op_inner(name, fn, tensor_args, kwargs, multi_output)


def _apply_op_inner(name, fn, tensor_args, kwargs, multi_output):
    from .tensor import Tensor

    kwargs = kwargs or {}
    tensors: List[Optional[Tensor]] = []
    vals: List[Any] = []
    for a in tensor_args:
        if isinstance(a, Tensor):
            tensors.append(a)
            vals.append(a._value)
        else:
            tensors.append(None)
            vals.append(a)

    cast_to = _amp_cast_dtype(name)
    vals = _harmonize_devices(vals)

    tracing = any(_is_tracer(v) for v in vals)
    need_grad = (not tracing) and _tape.is_grad_enabled() and any(
        t is not None and not t.stop_gradient for t in tensors)

    if need_grad:
        # Differentiate only w.r.t. inexact-dtype inputs that require grad.
        diff_idx = [
            i for i, (t, v) in enumerate(zip(tensors, vals))
            if t is not None and not t.stop_gradient
            and isinstance(v, (Array, np.ndarray))
            and jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)
        ]
        if not diff_idx:
            need_grad = False

    if not need_grad:
        if cast_to is not None:
            vals = [_amp_cast(v, cast_to) for v in vals]
        out_vals = fn(*vals, **kwargs)
        outs = _wrap_outputs(name, out_vals, multi_output, node=None)
    else:
        def closed(*diff_vals):
            full = list(vals)
            for i, dv in zip(diff_idx, diff_vals):
                full[i] = dv
            if cast_to is not None:
                # AMP cast INSIDE the differentiated closure so the VJP
                # returns cotangents in each input's original dtype.
                full = [_amp_cast(v, cast_to) for v in full]
            return fn(*full, **kwargs)

        primals = [vals[i] for i in diff_idx]
        out_vals, vjp_fn = jax.vjp(closed, *primals)
        out_is_tuple = isinstance(out_vals, tuple)
        flat_outs = out_vals if out_is_tuple else (out_vals,)
        out_meta = [(tuple(o.shape), o.dtype) for o in flat_outs]

        # For create_graph backward the re-derived VJP must treat EVERY
        # tensor input as an argument (not a baked closure constant):
        # a stop_gradient tensor (e.g. a static.data feed) still has to
        # enter the recorded grad op as a symbolic input so program
        # capture replays it with the run's value.
        tensor_idx = [i for i, t in enumerate(tensors) if t is not None]

        def closed_all(*tvals):
            full = list(vals)
            for i, tv in zip(tensor_idx, tvals):
                full[i] = tv
            if cast_to is not None:
                full = [_amp_cast(v, cast_to) for v in full]
            return fn(*full, **kwargs)

        node = _tape.GradNode(name, vjp_fn, [tensors[i] for i in diff_idx],
                              out_meta, out_is_tuple=out_is_tuple,
                              raw_fn=closed_all)
        node.raw_all_inputs = [tensors[i] for i in tensor_idx]
        node.raw_diff_pos = tuple(tensor_idx.index(i) for i in diff_idx)
        outs = _wrap_outputs(name, out_vals, multi_output, node=node)

    if get_flag("check_nan_inf"):
        flat = out_vals if isinstance(out_vals, tuple) else (out_vals,)
        _check_nan_inf(name, flat)
    dbg = _amp_debug_hook[0]
    if dbg is not None and not tracing:
        flat = out_vals if isinstance(out_vals, tuple) else (out_vals,)
        dbg(name, flat)
    rec = _sot_recorder[0]
    if rec is not None and not tracing:
        rec.record(name, fn, tensor_args, kwargs, outs, multi_output,
                   cast_to)
    return outs


def _wrap_outputs(name, out_vals, multi_output, node):
    from .tensor import Tensor

    if isinstance(out_vals, tuple):
        outs = []
        for i, v in enumerate(out_vals):
            t = Tensor._from_value(v)
            if node is not None and jnp.issubdtype(v.dtype, jnp.inexact):
                # only inexact outputs participate in the autograd graph;
                # integer outputs (topk indices, argsort, ...) stay
                # stop_gradient leaves
                t._grad_node = node
                t._out_index = i
                t.stop_gradient = False
            outs.append(t)
        return tuple(outs)
    t = Tensor._from_value(out_vals)
    if node is not None and jnp.issubdtype(out_vals.dtype, jnp.inexact):
        t._grad_node = node
        t._out_index = 0
        t.stop_gradient = False
    return (t,) if multi_output else t
