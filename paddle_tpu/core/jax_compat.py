"""Version shims for jax APIs that moved between 0.4.x and >=0.5.

One home for the dual spellings (used by ops/pallas_kernels,
distributed/pipelining, jit/train_step) so the branch logic cannot
drift between call sites.
"""
from __future__ import annotations

import jax


def shard_map_compat(f, mesh, in_specs, out_specs, manual_axes=None,
                     check=False):
    """shard_map across both jax APIs.

    manual_axes: set of axis names to run manually (None = all axes).
    check: run the vma/replication checker where the API supports it
    (jax >= 0.5 check_vma; 0.4.x always runs with check_rep=False — its
    checker has no rules for pallas outputs / several collectives).
    jax >= 0.5 spells this jax.shard_map(axis_names=..., check_vma=...);
    0.4.x has jax.experimental.shard_map with check_rep.  0.4.x cannot
    lower partial-manual axis_index (SPMD PartitionId UNIMPLEMENTED),
    so a manual_axes subset degrades to all-manual there — correct for
    every in-repo caller, whose non-manual axes are trivial/replicated.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh,
                             axis_names=set(manual_axes or
                                            mesh.axis_names),
                             in_specs=in_specs, out_specs=out_specs,
                             check_vma=check)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def axis_size(axis_name):
    """Static size of a manual mesh axis (>=0.5 lax.axis_size; 0.4.x
    core.axis_frame returns the size directly)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    from jax.core import axis_frame
    return axis_frame(axis_name)
