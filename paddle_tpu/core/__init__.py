from . import dtypes
from . import flags
from . import device
from .tensor import Tensor, to_tensor
from .flags import get_flags, set_flags, define_flag
from .device import (Place, CPUPlace, TPUPlace, CustomPlace, set_device,
                     get_device, device_guard, device_count,
                     is_compiled_with_tpu, synchronize)
