"""Dtype system.

TPU-native equivalent of the reference's DataType enum
(reference: paddle/phi/common/data_type.h) — here dtypes are thin wrappers
over numpy/jax dtypes with paddle-style string names. bfloat16 is first-class
(it is the TPU MXU's native reduced precision).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import dtypes as _jax_dtypes

bfloat16 = jnp.bfloat16
float16 = jnp.float16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_NAME_TO_DTYPE = {
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float16": float16,
    "fp16": float16,
    "half": float16,
    "float32": float32,
    "fp32": float32,
    "float": float32,
    "float64": float64,
    "fp64": float64,
    "double": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "int": int32,
    "long": int64,
    "uint8": uint8,
    "uint16": uint16,
    "uint32": uint32,
    "uint64": uint64,
    "bool": bool_,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (str / np.dtype / jnp dtype) to a
    canonical numpy dtype object (with bfloat16 extended-dtype support)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            dtype = _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}")
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical paddle-style name of a dtype."""
    d = np.dtype(dtype)
    if d == _jax_dtypes.bfloat16:
        return "bfloat16"
    return d.name


def is_floating_point(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.integer)


def is_complex(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.complexfloating)


def is_inexact(dtype) -> bool:
    d = convert_dtype(dtype)
    return jnp.issubdtype(d, jnp.inexact)


_DEFAULT_DTYPE = [np.dtype("float32")]


def get_default_dtype():
    return _DEFAULT_DTYPE[0]


def set_default_dtype(dtype):
    d = convert_dtype(dtype)
    if not is_floating_point(d):
        raise TypeError("default dtype must be floating point, got %s" % d)
    _DEFAULT_DTYPE[0] = d
