"""Device / place management.

Capability parity with the reference's Place + DeviceManager
(reference: paddle/phi/common/place.h, paddle/phi/backends/device_manager.h:134,
context pool paddle/phi/backends/context_pool.h).  On TPU the device runtime is
PJRT, surfaced through JAX; a "place" is a thin handle to a jax.Device.

The reference's hardware-plugin C ABI (paddle/phi/backends/device_ext.h) maps
to the PJRT C API plugin mechanism — selecting a platform here selects a PJRT
client underneath.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax


class Place:
    """Base device handle (reference: paddle/phi/common/place.h)."""

    device_type: str = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    @property
    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            # Fall back to whatever the default backend exposes.
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    """The TPU place — the whole point of this framework.

    Replaces the reference's GPUPlace/CUDAPlace (paddle/phi/common/place.h)."""
    device_type = "tpu"


class CustomPlace(Place):
    """Third-party accelerator place (reference: custom device plugin,
    paddle/phi/backends/custom/custom_device.cc:1059). Under PJRT a custom
    platform is just another client name."""

    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


def _platform_of(d: jax.Device) -> str:
    p = d.platform
    return "tpu" if p in ("tpu", "axon") else p


_CURRENT_DEVICE: list[Optional[Place]] = [None]


def _default_place() -> Place:
    d = jax.devices()[0]
    plat = _platform_of(d)
    if plat == "tpu":
        return TPUPlace(0)
    if plat == "cpu":
        return CPUPlace(0)
    return CustomPlace(plat, 0)


def get_device() -> str:
    """Current device string, e.g. 'tpu:0' (parity:
    python/paddle/device/__init__.py get_device)."""
    p = _CURRENT_DEVICE[0] or _default_place()
    return f"{p.device_type}:{p.device_id}"


def get_place() -> Place:
    return _CURRENT_DEVICE[0] or _default_place()


def set_device(device: str) -> Place:
    """Select the device new tensors land on, e.g. set_device('tpu')
    (parity: python/paddle/device/__init__.py set_device)."""
    if ":" in device:
        dev_type, idx = device.split(":")
        idx = int(idx)
    else:
        dev_type, idx = device, 0
    dev_type = {"gpu": "tpu"}.get(dev_type, dev_type)  # be forgiving
    if dev_type == "cpu":
        place: Place = CPUPlace(idx)
    elif dev_type == "tpu":
        place = TPUPlace(idx)
    else:
        place = CustomPlace(dev_type, idx)
    _CURRENT_DEVICE[0] = place
    return place


@contextlib.contextmanager
def device_guard(device: str):
    old = _CURRENT_DEVICE[0]
    set_device(device)
    try:
        yield
    finally:
        _CURRENT_DEVICE[0] = old


def device_count(device_type: str = "tpu") -> int:
    return len([d for d in jax.devices() if _platform_of(d) == device_type])


def is_compiled_with_tpu() -> bool:
    return device_count("tpu") > 0


def synchronize():
    """Block until all outstanding device work is done (parity:
    paddle.device.synchronize)."""
    jax.effects_barrier()


def device_group_key(value):
    """Hashable identity of the device set an array is committed to, or
    None when unknown.  Used to group per-submesh work (pipeline stages
    place parameters on disjoint submeshes; one jitted computation cannot
    mix device sets)."""
    try:
        return frozenset(d.id for d in value.devices())
    except Exception:
        return None
