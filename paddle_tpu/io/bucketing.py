"""Length-bucketing for static-shape compilation.

SURVEY.md §7 "hard parts": variable sequence lengths are the dynamic-
shape case the reference handles by being eager; under XLA every new
shape is a recompile, so the TPU-native policy is bucketing + padding —
group samples by length into a small set of buckets and pad each batch
to its bucket boundary, bounding the number of compiled executables to
the bucket count.

API shape follows the reference's sampler family (python/paddle/io/
BatchSampler) so it drops into DataLoader(batch_sampler=...).
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence

import numpy as np

from .sampler import Sampler

__all__ = ["BucketedBatchSampler", "pad_to_bucket", "default_buckets"]


def default_buckets(max_len: int, n_buckets: int = 8) -> List[int]:
    """Geometric bucket boundaries up to max_len, multiples of 8 (TPU
    sublane) — e.g. max_len=2048, n=8 → [16, 32, 64, ..., 2048]."""
    out = []
    b = max(8, max_len >> (n_buckets - 1))
    b = int(np.ceil(b / 8) * 8)
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(int(np.ceil(max_len / 8) * 8))
    return out


def pad_to_bucket(seq, buckets: Sequence[int], pad_value=0):
    """Pad a 1-D/2-D numpy array (or list) along its last axis to the
    smallest bucket >= its length.  Returns (padded, true_length)."""
    arr = np.asarray(seq)
    length = arr.shape[-1]
    for b in sorted(buckets):
        if length <= b:
            width = [(0, 0)] * (arr.ndim - 1) + [(0, b - length)]
            return np.pad(arr, width, constant_values=pad_value), length
    raise ValueError(
        f"sequence length {length} exceeds the largest bucket "
        f"{max(buckets)}")


class BucketedBatchSampler(Sampler):
    """Batches indices whose sample lengths share a bucket, so every
    batch pads to one static shape (bounded recompiles).

    ``lengths``: per-sample lengths (list/array) or a callable
    ``idx -> length``.  Partial bucket remainders are emitted as smaller
    final batches unless drop_last.
    """

    def __init__(self, lengths, buckets: Sequence[int], batch_size: int,
                 shuffle: bool = False, drop_last: bool = False,
                 seed: Optional[int] = None, num_samples: Optional[int]
                 = None):
        if callable(lengths):
            if num_samples is None:
                raise ValueError(
                    "num_samples is required when lengths is a callable")
            self._lengths = [int(lengths(i)) for i in range(num_samples)]
        else:
            self._lengths = [int(l) for l in lengths]
        self.buckets = sorted(int(b) for b in buckets)
        if self._lengths and max(self._lengths) > self.buckets[-1]:
            raise ValueError(
                f"max sample length {max(self._lengths)} exceeds the "
                f"largest bucket {self.buckets[-1]}")
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self._epoch = 0

    def bucket_of(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(f"length {length} exceeds buckets")

    def _make_batches(self) -> List[List[int]]:
        per_bucket = {b: [] for b in self.buckets}
        order = np.arange(len(self._lengths))
        if self.shuffle:
            rng = np.random.RandomState(
                None if self.seed is None else self.seed + self._epoch)
            rng.shuffle(order)
        for idx in order:
            per_bucket[self.bucket_of(self._lengths[idx])].append(int(idx))
        batches = []
        for b in self.buckets:
            ids = per_bucket[b]
            for i in range(0, len(ids), self.batch_size):
                chunk = ids[i:i + self.batch_size]
                if len(chunk) < self.batch_size and self.drop_last:
                    continue
                batches.append(chunk)
        if self.shuffle:
            rng = np.random.RandomState(
                None if self.seed is None else self.seed + self._epoch)
            rng.shuffle(batches)
        return batches

    def __iter__(self) -> Iterator[List[int]]:
        self._epoch += 1
        return iter(self._make_batches())

    def __len__(self) -> int:
        # O(buckets): batch count is shuffle-invariant, so no need to
        # rebuild (and reshuffle) the batch list just to count it
        per_bucket = {b: 0 for b in self.buckets}
        for length in self._lengths:
            per_bucket[self.bucket_of(length)] += 1
        total = 0
        for n in per_bucket.values():
            total += n // self.batch_size
            if n % self.batch_size and not self.drop_last:
                total += 1
        return total
