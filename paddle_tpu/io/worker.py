"""DataLoader worker process loop (parity:
python/paddle/io/dataloader/worker.py — _worker_loop feeding shared-memory
batches back to the trainer process).

Each worker owns one native shm ring (io/_native/ringbuf.cc) as producer;
the parent consumes rings round-robin so map-style batch order is
preserved.  Workers ship raw sample pytrees (numpy buffers memcpy'd, no
pickling of array data); the parent runs collate, keeping jax strictly out
of forked children.
"""
from __future__ import annotations

import os
import pickle
import sys
import traceback

import numpy as np


def _to_plain(x):
    """Strip framework Tensors down to numpy before crossing the process
    boundary.  This is the only jax touch allowed in a forked child: a
    host fetch of a CPU-resident array the child itself created (datasets
    should prefer returning numpy; device state from the parent is never
    exercised here)."""
    from ..core.tensor import Tensor
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    if isinstance(x, (list, tuple)):
        out = [_to_plain(i) for i in x]
        return tuple(out) if isinstance(x, tuple) else out
    if isinstance(x, dict):
        return {k: _to_plain(v) for k, v in x.items()}
    return x


def worker_loop(dataset, my_batches, session, capacity, worker_id,
                num_workers, worker_init_fn, iterable, batch_size,
                drop_last):
    """Entry point of a forked worker process."""
    from . import dataloader as dl_mod
    from .shm_ring import ShmRing, encode_batch

    ring = ShmRing(f"/{session}-{worker_id}", capacity, owner=False)
    dl_mod._worker_info = dl_mod.WorkerInfo(worker_id, num_workers, dataset)
    try:
        if worker_init_fn is not None:
            worker_init_fn(worker_id)
        if iterable:
            # reference semantics (dataloader_iter.py + worker.py): every
            # worker iterates the WHOLE dataset; de-duplication is the
            # dataset's job via get_worker_info() (anything else would
            # double-shard datasets that already split themselves)
            batch = []
            per_batch = batch_size or 1    # match _iter_iterable
            for sample in dataset:
                batch.append(sample)
                if len(batch) == per_batch:
                    ring.send_msg(b"B" + encode_batch(_to_plain(batch)))
                    batch = []
            if batch and not drop_last:
                ring.send_msg(b"B" + encode_batch(_to_plain(batch)))
        else:
            for batch_idx in my_batches:
                samples = [dataset[i] for i in batch_idx]
                ring.send_msg(b"B" + encode_batch(_to_plain(samples)))
    except KeyboardInterrupt:
        pass
    except BaseException:
        try:
            ring.send_msg(b"E" + pickle.dumps(traceback.format_exc()))
        except Exception:
            pass
    finally:
        ring.close_write()
        ring.detach()
        os._exit(0)   # skip atexit: the child must not tear down jax state
