"""ctypes driver for the native shared-memory ring (io/_native/ringbuf.cc).

Builds the .so once per machine into ``~/.cache/paddle_tpu/native`` (the
package dir may be read-only at runtime), loads it via ctypes, and exposes
a message-framed API on top of the byte ring:

  frame := u64 payload_size | payload
  batch payload := pickle of a template pytree where every numpy array is
  replaced by a (marker, dtype, shape) stub + the raw array buffers
  appended — arrays travel as memcpy'd bytes, not pickles.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import pickle
import struct
import subprocess
import sys
import threading
from typing import List, Optional, Tuple

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "_native", "ringbuf.cc")
_LIB = [None]
_LIB_LOCK = threading.Lock()

from .._native_build import NativeBuildError, build_shared_lib  # noqa: E402


def _build_lib() -> str:
    return build_shared_lib("libringbuf", [_SRC])


def _lib():
    if _LIB[0] is None:
        with _LIB_LOCK:
            if _LIB[0] is None:
                lib = ctypes.CDLL(_build_lib())
                lib.rb_open.restype = ctypes.c_void_p
                lib.rb_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                        ctypes.c_int]
                lib.rb_write.restype = ctypes.c_int64
                lib.rb_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64]
                lib.rb_read.restype = ctypes.c_int64
                lib.rb_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_uint64]
                lib.rb_read_timeout.restype = ctypes.c_int64
                lib.rb_read_timeout.argtypes = [ctypes.c_void_p,
                                                ctypes.c_void_p,
                                                ctypes.c_uint64,
                                                ctypes.c_uint64]
                lib.rb_readable.restype = ctypes.c_uint64
                lib.rb_readable.argtypes = [ctypes.c_void_p]
                lib.rb_is_closed.restype = ctypes.c_int
                lib.rb_is_closed.argtypes = [ctypes.c_void_p]
                lib.rb_close_write.argtypes = [ctypes.c_void_p]
                lib.rb_detach.argtypes = [ctypes.c_void_p]
                lib.rb_unlink.argtypes = [ctypes.c_char_p]
                _LIB[0] = lib
    return _LIB[0]


def native_available() -> bool:
    try:
        _lib()
        return True
    except NativeBuildError:
        return False


class ShmRing:
    """One SPSC byte ring in POSIX shm, message-framed."""

    def __init__(self, name: str, capacity: int, owner: bool):
        self._lib = _lib()
        self.name = name.encode()
        self.owner = owner
        self._h = self._lib.rb_open(self.name, capacity, 1 if owner else 0)
        if not self._h:
            raise OSError(f"rb_open({name}) failed")

    # -- producer --
    def send_msg(self, payload: bytes):
        frame = struct.pack("<Q", len(payload)) + payload
        rc = self._lib.rb_write(self._h, frame, len(frame))
        if rc < 0:
            raise OSError("ring write failed (message larger than ring?)")

    def close_write(self):
        self._lib.rb_close_write(self._h)

    # -- consumer --
    class Timeout(Exception):
        pass

    def _read_exact(self, buf, n, timeout_us):
        if timeout_us is None:
            return self._lib.rb_read(self._h, buf, n)
        return self._lib.rb_read_timeout(self._h, buf, n, timeout_us)

    def recv_msg(self, timeout_us: Optional[int] = None) -> Optional[bytes]:
        """Blocking; None on clean EOF; raises ShmRing.Timeout after
        `timeout_us` of no progress (so callers can run liveness checks
        on the producer and retry)."""
        hdr = ctypes.create_string_buffer(8)
        rc = self._read_exact(hdr, 8, timeout_us)
        if rc == 0:
            return None
        if rc == -2:
            raise ShmRing.Timeout()
        if rc != 8:
            raise OSError("ring read failed (truncated frame)")
        (size,) = struct.unpack("<Q", hdr.raw)
        buf = ctypes.create_string_buffer(size)
        if size:
            rc = self._read_exact(buf, size, timeout_us)
            if rc != size:
                raise OSError("ring read failed mid-frame "
                              "(producer died while writing?)")
        return buf.raw

    def readable(self) -> int:
        return int(self._lib.rb_readable(self._h))

    def is_closed(self) -> bool:
        return bool(self._lib.rb_is_closed(self._h))

    def detach(self):
        if self._h:
            self._lib.rb_detach(self._h)
            self._h = None

    def unlink(self):
        self._lib.rb_unlink(self.name)

    def __del__(self):
        try:
            self.detach()
            if self.owner:
                self.unlink()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# batch (de)serialization: numpy buffers as raw bytes, structure as pickle
# ---------------------------------------------------------------------------
class _ArrayStub:
    __slots__ = ("idx", "dtype", "shape")

    def __init__(self, idx, dtype, shape):
        self.idx = idx
        self.dtype = dtype
        self.shape = shape


def encode_batch(obj) -> bytes:
    buffers: List[bytes] = []

    def strip(x):
        if isinstance(x, np.ndarray):
            stub = _ArrayStub(len(buffers), x.dtype.str, x.shape)
            buffers.append(np.ascontiguousarray(x).tobytes())
            return stub
        if isinstance(x, (list, tuple)):
            out = [strip(i) for i in x]
            return tuple(out) if isinstance(x, tuple) else out
        if isinstance(x, dict):
            return {k: strip(v) for k, v in x.items()}
        return x

    template = strip(obj)
    tpl = pickle.dumps(template, protocol=4)
    parts = [struct.pack("<QI", len(tpl), len(buffers)), tpl]
    for b in buffers:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    return b"".join(parts)


def decode_batch(payload: bytes):
    tpl_len, n_buf = struct.unpack_from("<QI", payload, 0)
    off = 12
    template = pickle.loads(payload[off:off + tpl_len])
    off += tpl_len
    buffers = []    # (offset, nbytes) spans into payload
    for _ in range(n_buf):
        (blen,) = struct.unpack_from("<Q", payload, off)
        off += 8
        buffers.append((off, blen))
        off += blen

    def fill(x):
        if isinstance(x, _ArrayStub):
            boff, blen = buffers[x.idx]
            dt = np.dtype(x.dtype)
            # one copy (off the shared frame) so the result is writable
            # like the single-process path's arrays
            return np.frombuffer(payload, dtype=dt,
                                 count=blen // dt.itemsize,
                                 offset=boff).reshape(x.shape).copy()
        if isinstance(x, (list, tuple)):
            out = [fill(i) for i in x]
            return tuple(out) if isinstance(x, tuple) else out
        if isinstance(x, dict):
            return {k: fill(v) for k, v in x.items()}
        return x

    return fill(template)
