"""Dataset abstractions (parity: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect
from typing import List, Sequence

import numpy as np

from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = list(tensors)
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cum = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cum[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        i = bisect.bisect_right(self.cum, idx)
        prev = self.cum[i - 1] if i > 0 else 0
        return self.datasets[i][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    from ..ops.random import randperm
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * total)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    perm = randperm(total).numpy().tolist()
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l]))
        off += l
    return out
