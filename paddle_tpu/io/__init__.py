"""Data pipeline.

Parity: python/paddle/io/ (reference, SURVEY.md #65 — Dataset/IterableDataset,
samplers, DataLoader with multiprocess workers + shared-memory tensors,
dataloader_iter.py:150,358).

TPU-native design: the loader produces host numpy batches on background
threads (double-buffered prefetch) and the framework moves them to HBM on
first use; multi-worker mode uses a process pool feeding the same prefetch
queue.  (C++ shared-memory ring buffer is a later optimization slot.)
"""
from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, Subset, ConcatDataset, random_split)
from .bucketing import (BucketedBatchSampler, pad_to_bucket,
                        default_buckets)
from .sampler import (Sampler, SequenceSampler, RandomSampler, BatchSampler,
                      DistributedBatchSampler, WeightedRandomSampler,
                      SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info
