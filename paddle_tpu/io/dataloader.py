"""DataLoader.

Parity: python/paddle/io/dataloader/dataloader_iter.py:150 (single-process)
and :358 (multi-process) in the reference.  Here: a background
thread/process pool maps indices -> samples -> collated numpy batches into a
bounded prefetch queue (the analog of the reference's blocking queue +
shared-memory tensels); device transfer happens lazily when a batch Tensor
first hits an op.
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    dataset: Any


_worker_info: Optional[WorkerInfo] = None


def get_worker_info():
    return _worker_info


def _queue_wait_histogram():
    """Consumer-side wait for the next prefetched batch: ~0 means the
    loader keeps ahead of the device; a fat tail means decode/augment
    (or the shm ring) is the training bottleneck."""
    from ..observability import histogram
    return histogram(
        "dataloader_queue_wait_seconds",
        "time the consumer blocked waiting on the prefetch queue/ring")


def default_collate_fn(batch):
    """Stack samples into batch arrays (parity:
    python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return batch


class DataLoader:
    """Parity: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(2, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        # resumable-iterator state (fault-tolerant training): position
        # within the CURRENT epoch, and a pending fast-forward request
        self._batches_yielded = 0
        self._resume_skip = 0
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    # -- resumable-iterator state (parity: the reference's resumable
    # dataloader position in its distributed checkpoint layer) -----------
    def state_dict(self):
        """Position of the live iterator within its epoch — checkpoint
        this and feed it back through :meth:`set_state_dict` to resume
        mid-epoch.  (A consumer that prefetches ahead of its compute —
        like Engine.fit's one-batch lookahead — should instead record
        its own completed-step count and pass that to set_state_dict.)"""
        return {"batches_yielded": int(self._batches_yielded)}

    def set_state_dict(self, state):
        """Arm the NEXT ``iter()`` to fast-forward ``batches_yielded``
        batches.  Map-style single-process loading skips by advancing
        the sampler only (no sample is decoded); prefetch/worker paths
        decode-and-discard.  Deterministic resume additionally needs a
        deterministic sampler order (shuffle=False, or a seeded
        sampler)."""
        self._resume_skip = max(0, int(state.get("batches_yielded", 0)))

    def __iter__(self):
        skip, self._resume_skip = self._resume_skip, 0
        # position set EAGERLY: state_dict() between iter() and the
        # first next() must already report the fast-forwarded position,
        # not 0 (a preemption landing there would otherwise rewind the
        # whole epoch prefix on the following resume)
        self._batches_yielded = skip
        if self.num_workers > 0:
            from .shm_ring import native_available
            if self.use_shared_memory and native_available():
                return self._count(self._discard(
                    self._iter_multiprocess(), skip))
            if self._iterable:
                return self._count(self._discard(
                    self._iter_iterable(), skip))
            return self._count(self._discard(
                self._iter_prefetch(), skip))
        if self._iterable:
            return self._count(self._discard(
                self._iter_iterable(), skip))
        return self._count(self._iter_single(skip))

    def _count(self, gen):
        for item in gen:
            # count BEFORE handing the batch out: a consumer that
            # checkpoints state_dict() after training on batch k must
            # see position k+1, not k (or resume would replay a batch)
            self._batches_yielded += 1
            yield item

    @staticmethod
    def _discard(gen, skip):
        """Lazily drop the first ``skip`` batches (the generic resume
        path for worker-backed iterators, where batch k's bytes only
        exist by producing batches 0..k-1)."""
        for i, item in enumerate(gen):
            if i >= skip:
                yield item

    # -- multi-process workers over native shm rings --------------------------
    def _iter_multiprocess(self):
        """Parity: _DataLoaderIterMultiProcess (dataloader_iter.py:358):
        worker processes decode samples and stream them through
        shared-memory rings; the parent collates.  Workers are real
        processes (GIL-free decode), rings are the C++ SPSC byte rings in
        io/_native/ringbuf.cc."""
        import multiprocessing as mp
        import os as _os
        import pickle as _pickle
        import uuid

        from .shm_ring import ShmRing, decode_batch  # noqa: F811

        W = self.num_workers
        capacity = int(_os.environ.get("FLAGS_dataloader_ring_bytes",
                                       str(64 << 20)))
        session = f"pdtpu-{_os.getpid()}-{uuid.uuid4().hex[:8]}"
        rings = [ShmRing(f"/{session}-{w}", capacity, owner=True)
                 for w in range(W)]
        if self._iterable:
            shards = [None] * W
        else:
            batches = list(self.batch_sampler)
            shards = [batches[w::W] for w in range(W)]

        ctx = mp.get_context("fork")
        from .worker import worker_loop
        procs = []
        for w in range(W):
            p = ctx.Process(
                target=worker_loop,
                args=(self.dataset, shards[w], session, capacity, w, W,
                      self.worker_init_fn, self._iterable,
                      self.batch_size if self._iterable else None,
                      self.drop_last if self._iterable else False),
                daemon=True)
            p.start()
            procs.append(p)

        import time as _time
        wait_hist = _queue_wait_histogram()
        alive = [True] * W
        try:
            w = 0
            while any(alive):
                if not alive[w]:
                    w = (w + 1) % W
                    continue
                t_wait = _time.perf_counter()
                while True:
                    try:
                        msg = rings[w].recv_msg(timeout_us=1_000_000)
                        wait_hist.observe(_time.perf_counter() - t_wait)
                        break
                    except ShmRing.Timeout:
                        # watchdog: a SIGKILL'd/segfaulted worker never
                        # hangs up the ring — detect it instead of
                        # spinning forever (reference dataloader watchdog)
                        if not procs[w].is_alive():
                            raise RuntimeError(
                                "DataLoader worker %d died unexpectedly "
                                "(exitcode=%s)" % (w, procs[w].exitcode))
                if msg is None:            # clean EOF from this worker
                    alive[w] = False
                    w = (w + 1) % W
                    continue
                if msg[:1] == b"E":
                    raise RuntimeError(
                        "DataLoader worker %d failed:\n%s"
                        % (w, _pickle.loads(msg[1:])))
                samples = decode_batch(msg[1:])
                yield self.collate_fn(list(samples))
                w = (w + 1) % W
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            for r in rings:
                r.detach()
                r.unlink()

    # -- single process ------------------------------------------------------
    def _iter_single(self, skip=0):
        # resume fast-forward: advance the sampler WITHOUT touching the
        # dataset — skipping 10k batches costs index arithmetic, not I/O
        for n, batch_idx in enumerate(self.batch_sampler):
            if n < skip:
                continue
            samples = [self.dataset[i] for i in batch_idx]
            yield self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == (self.batch_size or 1):
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    # -- threaded prefetch (reference's multi-worker analog) -----------------
    def _iter_prefetch(self):
        q: "queue.Queue" = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()
        # a consumer that stops early (break / exception / gc of this
        # generator) sets `stop`; the producer's bounded put polls it so
        # it can never block forever on a full queue the consumer will
        # never drain again
        stop = threading.Event()

        def _put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            global _worker_info
            _worker_info = WorkerInfo(0, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(0)
            pool = ThreadPoolExecutor(self.num_workers)
            try:
                def load(batch_idx):
                    samples = [self.dataset[i] for i in batch_idx]
                    return self.collate_fn(samples)

                # bounded submission window (Executor.map would submit
                # the WHOLE sampler eagerly, letting finished batches
                # pile up in memory ahead of a slow consumer — the queue
                # bound must also bound the in-flight work)
                from collections import deque
                window = q.maxsize + self.num_workers
                pending: "deque" = deque()
                sampler_it = iter(self.batch_sampler)
                exhausted = False
                while pending or not exhausted:
                    while not exhausted and len(pending) < window \
                            and not stop.is_set():
                        try:
                            pending.append(
                                pool.submit(load, next(sampler_it)))
                        except StopIteration:
                            exhausted = True
                    if not pending:
                        break
                    if not _put(pending.popleft().result()):
                        return
            except Exception as e:  # surface worker errors to the consumer
                _put(e)
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
                _put(sentinel)

        t = threading.Thread(target=producer, daemon=True,
                             name="pdtpu-dataloader-prefetch")
        t.start()
        import time as _time
        wait_hist = _queue_wait_histogram()
        try:
            while True:
                t_wait = _time.perf_counter()
                item = q.get()
                wait_hist.observe(_time.perf_counter() - t_wait)
                if item is sentinel:
                    break
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so a put blocked on the full queue returns promptly
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
