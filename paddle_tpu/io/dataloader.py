"""DataLoader.

Parity: python/paddle/io/dataloader/dataloader_iter.py:150 (single-process)
and :358 (multi-process) in the reference.  Here: a background
thread/process pool maps indices -> samples -> collated numpy batches into a
bounded prefetch queue (the analog of the reference's blocking queue +
shared-memory tensels); device transfer happens lazily when a batch Tensor
first hits an op.
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Optional

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    dataset: Any


_worker_info: Optional[WorkerInfo] = None


def get_worker_info():
    return _worker_info


def default_collate_fn(batch):
    """Stack samples into batch arrays (parity:
    python/paddle/io/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._value) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    return batch


class DataLoader:
    """Parity: paddle.io.DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = max(2, int(prefetch_factor))
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle,
                batch_size=batch_size if batch_size is not None else 1,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._iterable:
            return self._iter_iterable()
        if self.num_workers == 0:
            return self._iter_single()
        return self._iter_prefetch()

    # -- single process ------------------------------------------------------
    def _iter_single(self):
        for batch_idx in self.batch_sampler:
            samples = [self.dataset[i] for i in batch_idx]
            yield self.collate_fn(samples)

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == (self.batch_size or 1):
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    # -- threaded prefetch (reference's multi-worker analog) -----------------
    def _iter_prefetch(self):
        q: "queue.Queue" = queue.Queue(
            maxsize=self.num_workers * self.prefetch_factor)
        sentinel = object()

        def producer():
            global _worker_info
            _worker_info = WorkerInfo(0, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(0)
            try:
                with ThreadPoolExecutor(self.num_workers) as pool:
                    def load(batch_idx):
                        samples = [self.dataset[i] for i in batch_idx]
                        return self.collate_fn(samples)

                    for out in pool.map(load, self.batch_sampler):
                        q.put(out)
            except Exception as e:  # surface worker errors to the consumer
                q.put(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            if isinstance(item, Exception):
                raise item
            yield item
