// Shared-memory ring buffer backing the multiprocess DataLoader.
//
// Parity: the reference's C++ data-pipeline core — shared-memory tensor
// transport between dataloader worker processes and the trainer
// (python/paddle/io/dataloader/worker.py + the buffered readers in
// paddle/fluid/operators/reader/; shm serialization in
// python/paddle/incubate/multiprocessing/reductions.py).
//
// Design: one single-producer/single-consumer byte ring per worker, in a
// mmap'd POSIX shared-memory segment.  Lock-free: the producer owns
// `head`, the consumer owns `tail` (C11 atomics, release/acquire).  The
// payload protocol (array headers + raw buffers) lives in Python; this
// file only moves bytes — memcpy into and out of the ring, wrapping at
// the end, blocking with a short adaptive sleep when full/empty.
//
// Built once per machine with g++ -O2 -shared -fPIC (see shm_ring.py) and
// driven through ctypes, so the GIL is released for the whole blocking
// read/write — the decode thread never stalls the training loop.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;      // bytes written (producer cursor)
  std::atomic<uint64_t> tail;      // bytes consumed (consumer cursor)
  std::atomic<uint32_t> closed;    // producer hung up
  uint32_t _pad;
  uint64_t capacity;               // data area size in bytes
};

struct Ring {
  RingHeader* hdr;
  uint8_t* data;
  size_t map_size;
};

void sleep_ns(long ns) {
  struct timespec ts = {0, ns};
  nanosleep(&ts, nullptr);
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a ring of `capacity` data bytes in
// the shm segment `name`. Returns an opaque handle or null.
void* rb_open(const char* name, uint64_t capacity, int owner) {
  size_t map_size = sizeof(RingHeader) + capacity;
  int flags = owner ? (O_CREAT | O_RDWR | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0 && owner && errno == EEXIST) {
    shm_unlink(name);
    fd = shm_open(name, flags, 0600);
  }
  if (fd < 0) return nullptr;
  if (owner && ftruncate(fd, (off_t)map_size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Ring* r = new Ring;
  r->hdr = reinterpret_cast<RingHeader*>(mem);
  r->data = reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader);
  r->map_size = map_size;
  if (owner) {
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->closed.store(0, std::memory_order_relaxed);
    r->hdr->capacity = capacity;
  }
  return r;
}

// Blocking write of n bytes; returns n, or -1 if the consumer vanished
// (ring closed from the read side is not tracked: close is producer->
// consumer only, the parent kills workers on teardown).
int64_t rb_write(void* handle, const uint8_t* buf, uint64_t n) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  RingHeader* h = r->hdr;
  const uint64_t cap = h->capacity;
  if (n > cap) return -1;
  uint64_t written = 0;
  long backoff = 1000;  // 1us
  while (written < n) {
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t free_bytes = cap - (head - tail);
    if (free_bytes == 0) {
      sleep_ns(backoff);
      if (backoff < 200000) backoff *= 2;  // cap at 200us
      continue;
    }
    backoff = 1000;
    uint64_t chunk = n - written;
    if (chunk > free_bytes) chunk = free_bytes;
    uint64_t pos = head % cap;
    uint64_t until_wrap = cap - pos;
    uint64_t c1 = chunk < until_wrap ? chunk : until_wrap;
    memcpy(r->data + pos, buf + written, c1);
    if (chunk > c1) memcpy(r->data, buf + written + c1, chunk - c1);
    h->head.store(head + chunk, std::memory_order_release);
    written += chunk;
  }
  return (int64_t)n;
}

// Blocking read of exactly n bytes; returns n, 0 on clean EOF (producer
// closed and ring drained), -1 on protocol error.
int64_t rb_read(void* handle, uint8_t* buf, uint64_t n) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  RingHeader* h = r->hdr;
  const uint64_t cap = h->capacity;
  uint64_t got = 0;
  long backoff = 1000;
  while (got < n) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    uint64_t avail = head - tail;
    if (avail == 0) {
      if (h->closed.load(std::memory_order_acquire)) {
        // drained and producer gone
        return got == 0 ? 0 : -1;
      }
      sleep_ns(backoff);
      if (backoff < 200000) backoff *= 2;
      continue;
    }
    backoff = 1000;
    uint64_t chunk = n - got;
    if (chunk > avail) chunk = avail;
    uint64_t pos = tail % cap;
    uint64_t until_wrap = cap - pos;
    uint64_t c1 = chunk < until_wrap ? chunk : until_wrap;
    memcpy(buf + got, r->data + pos, c1);
    if (chunk > c1) memcpy(buf + got + c1, r->data, chunk - c1);
    h->tail.store(tail + chunk, std::memory_order_release);
    got += chunk;
  }
  return (int64_t)got;
}

// Like rb_read but gives up after timeout_us of no progress, returning -2.
// Lets the consumer interleave liveness checks on the producer process
// instead of spinning forever on a worker that died without hanging up.
//
// The timeout ONLY fires before any byte is consumed — once mid-message,
// returning -2 would leave the stream desynced on retry, so the wait is
// extended (30x) and expiry is a hard protocol error (-1).
int64_t rb_read_timeout(void* handle, uint8_t* buf, uint64_t n,
                        uint64_t timeout_us) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  RingHeader* h = r->hdr;
  const uint64_t cap = h->capacity;
  uint64_t got = 0;
  long backoff = 1000;
  uint64_t waited_ns = 0;
  const uint64_t limit_ns = timeout_us * 1000ull;
  while (got < n) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    uint64_t avail = head - tail;
    if (avail == 0) {
      if (h->closed.load(std::memory_order_acquire)) {
        return got == 0 ? 0 : -1;
      }
      if (got == 0 && waited_ns >= limit_ns) return -2;
      if (got > 0 && waited_ns >= 30 * limit_ns) return -1;
      sleep_ns(backoff);
      waited_ns += (uint64_t)backoff;
      if (backoff < 200000) backoff *= 2;
      continue;
    }
    backoff = 1000;
    waited_ns = 0;  // progress resets the clock
    uint64_t chunk = n - got;
    if (chunk > avail) chunk = avail;
    uint64_t pos = tail % cap;
    uint64_t until_wrap = cap - pos;
    uint64_t c1 = chunk < until_wrap ? chunk : until_wrap;
    memcpy(buf + got, r->data + pos, c1);
    if (chunk > c1) memcpy(buf + got + c1, r->data, chunk - c1);
    h->tail.store(tail + chunk, std::memory_order_release);
    got += chunk;
  }
  return (int64_t)got;
}

// Bytes currently readable (for polling round-robin consumers).
uint64_t rb_readable(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  return r->hdr->head.load(std::memory_order_acquire) -
         r->hdr->tail.load(std::memory_order_relaxed);
}

int rb_is_closed(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  return (int)r->hdr->closed.load(std::memory_order_acquire);
}

// Producer hang-up: consumer sees EOF after draining.
void rb_close_write(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  r->hdr->closed.store(1, std::memory_order_release);
}

void rb_detach(void* handle) {
  Ring* r = reinterpret_cast<Ring*>(handle);
  munmap(r->hdr, r->map_size);
  delete r;
}

void rb_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
