"""paddle.text.viterbi_decode (parity: python/paddle/text/
viterbi_decode.py — viterbi_decode + ViterbiDecoder).

TPU-native: the Viterbi recursion is a lax.scan over time steps — one
compiled kernel, batch-parallel — instead of the reference's CUDA
viterbi_decode kernel.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import apply_op
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag: bool = True, name=None):
    """Batched Viterbi decode (parity: text/viterbi_decode.py).

    potentials: [B, T, N] unary emissions; transition_params: [N, N]
    (with BOS=N-2, EOS=N-1 rows/cols when include_bos_eos_tag);
    lengths: [B] int64.  Returns (scores [B], paths [B, T])."""
    e = potentials._value if isinstance(potentials, Tensor) \
        else jnp.asarray(potentials)
    trans = transition_params._value \
        if isinstance(transition_params, Tensor) \
        else jnp.asarray(transition_params)
    B, T, N = e.shape
    if lengths is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = jnp.asarray(
            lengths._value if isinstance(lengths, Tensor) else lengths,
            jnp.int32)

    if include_bos_eos_tag:
        bos, eos = N - 2, N - 1
        alpha0 = e[:, 0] + trans[bos][None, :]
    else:
        alpha0 = e[:, 0]

    def step(carry, t):
        alpha, = carry
        # scores[b, i, j] = alpha[b, i] + trans[i, j] + e[b, t, j]
        scores = alpha[:, :, None] + trans[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)               # [B, N]
        best_score = jnp.max(scores, axis=1) + e[:, t]
        # sequences shorter than t keep their alpha frozen
        active = (t < lens)[:, None]
        new_alpha = jnp.where(active, best_score, alpha)
        return (new_alpha,), best_prev

    (alpha,), backptrs = lax.scan(
        step, (alpha0,), jnp.arange(1, T, dtype=jnp.int32))
    # backptrs: [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + trans[:, eos][None, :]

    last_tag = jnp.argmax(alpha, axis=-1)                    # [B]
    scores = jnp.max(alpha, axis=-1)

    def backtrace(carry, bp_t):
        tag, t = carry
        # bp_t: [B, N] pointers at step t+1; only follow while t+1 < len
        prev = jnp.take_along_axis(bp_t, tag[:, None], 1)[:, 0]
        use = (t + 1) < lens
        new_tag = jnp.where(use, prev, tag)
        return (new_tag, t - 1), new_tag

    ts = jnp.arange(T - 2, -1, -1, dtype=jnp.int32)
    (first_tag, _), rev_path = lax.scan(
        backtrace, (last_tag, jnp.int32(T - 2)), backptrs[::-1])
    path = jnp.concatenate([rev_path[::-1],
                            last_tag[None, :]], 0).T       # [B, T]
    return (Tensor._from_value(scores),
            Tensor._from_value(path.astype(jnp.int64)))


class ViterbiDecoder(Layer):
    """Parity: paddle.text.ViterbiDecoder."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(np.asarray(transitions, np.float32))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
