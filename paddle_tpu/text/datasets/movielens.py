"""Parity: python/paddle/text/datasets/movielens.py — MovieLens-1M
rating prediction over the ml-1m.zip layout (users.dat / movies.dat /
ratings.dat, '::'-separated)."""
from __future__ import annotations

import re
import zipfile

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []


class MovieInfo:
    """Parity: movielens.MovieInfo."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]


class UserInfo:
    """Parity: movielens.UserInfo."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = [1, 18, 25, 35, 45, 50, 56].index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """Parity: paddle.text.Movielens(data_file, mode, test_ratio,
    rand_seed)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        assert mode in ("train", "test")
        self.data_file = _require(data_file)
        self.mode = mode
        self.test_ratio = test_ratio
        self.rand_seed = rand_seed
        np.random.seed(rand_seed)
        self._load_meta_info()
        self._load_data()

    def _load_meta_info(self):
        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info = {}
        self.movie_title_dict = {}
        self.categories_dict = {}
        self.user_info = {}
        with zipfile.ZipFile(self.data_file) as package:
            for info in package.namelist():
                if "movies.dat" in info:
                    with package.open(info) as f:
                        for line in f:
                            line = line.decode("latin-1").strip()
                            idx, title, categories = line.split("::")
                            m = pattern.match(title)
                            title = m.group(1) if m else title
                            cats = categories.split("|")
                            for c in cats:
                                self.categories_dict.setdefault(
                                    c, len(self.categories_dict))
                            for w in title.split():
                                self.movie_title_dict.setdefault(
                                    w.lower(),
                                    len(self.movie_title_dict))
                            self.movie_info[int(idx)] = MovieInfo(
                                idx, cats, title)
                elif "users.dat" in info:
                    with package.open(info) as f:
                        for line in f:
                            line = line.decode("latin-1").strip()
                            uid, gender, age, job, _ = line.split("::")
                            self.user_info[int(uid)] = UserInfo(
                                uid, gender, age, job)

    def _load_data(self):
        self.data = []
        is_test = self.mode == "test"
        with zipfile.ZipFile(self.data_file) as package:
            ratings = [n for n in package.namelist()
                       if "ratings.dat" in n][0]
            with package.open(ratings) as f:
                for line in f:
                    line = line.decode("latin-1").strip()
                    if (np.random.rand() < self.test_ratio) == is_test:
                        uid, mid, rating, _ = line.split("::")
                        uid, mid = int(uid), int(mid)
                        if uid not in self.user_info or \
                                mid not in self.movie_info:
                            continue
                        usr = self.user_info[uid].value()
                        mov = self.movie_info[mid].value(
                            self.categories_dict, self.movie_title_dict)
                        self.data.append(
                            usr + mov + [[float(rating)]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)
