"""Parity: python/paddle/text/datasets/wmt14.py — WMT14 en-fr over the
wmt14 tar layout (*/src.dict, */trg.dict, <mode>/<mode> bitext with
tab-separated src/trg)."""
from __future__ import annotations

import tarfile

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


class WMT14(Dataset):
    """Parity: paddle.text.WMT14(data_file, mode, dict_size)."""

    def __init__(self, data_file=None, mode="train", dict_size=-1,
                 download=True):
        assert mode in ("train", "test", "gen")
        self.data_file = _require(data_file)
        self.mode = mode
        assert dict_size > 0
        self.dict_size = dict_size
        self._load_data()

    def _load_data(self):
        def to_dict(fd, size):
            out = {}
            for i, line in enumerate(fd):
                if i < size:
                    out[line.strip().decode()] = i
                else:
                    break
            return out

        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            src_name = [m.name for m in f
                        if m.name.endswith("src.dict")]
            trg_name = [m.name for m in f
                        if m.name.endswith("trg.dict")]
            assert len(src_name) == 1 and len(trg_name) == 1
            self.src_dict = to_dict(f.extractfile(src_name[0]),
                                    self.dict_size)
            self.trg_dict = to_dict(f.extractfile(trg_name[0]),
                                    self.dict_size)
            file_name = f"{self.mode}/{self.mode}"
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode().strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [self.src_dict.get(w, UNK_IDX)
                               for w in [START] + parts[0].split()
                               + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [self.trg_dict.get(w, UNK_IDX)
                               for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    trg_ids_next = trg_ids + [self.trg_dict[END]]
                    trg_ids = [self.trg_dict[START]] + trg_ids
                    self.src_ids.append(src_ids)
                    self.trg_ids.append(trg_ids)
                    self.trg_ids_next.append(trg_ids_next)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
