"""Parity: python/paddle/text/datasets/conll05.py — CoNLL-2005 SRL test
set over (data tar with test.wsj/words + test.wsj/props, word dict,
verb dict, target/label dict).  Items follow the reference's 9-slot
layout: word_ids, ctx_n2/n1/0/p1/p2 predicate-context ids, predicate
marks, predicate id, label ids."""
from __future__ import annotations

import gzip
import tarfile

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []

UNK_IDX = 0


class Conll05st(Dataset):
    """Parity: paddle.text.Conll05st(data_file, word_dict_file,
    verb_dict_file, target_dict_file)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None,
                 emb_file=None, download=True):
        self.data_file = _require(data_file)
        self.word_dict_file = _require(word_dict_file)
        self.verb_dict_file = _require(verb_dict_file)
        self.target_dict_file = _require(target_dict_file)
        self.word_dict = self._load_dict(self.word_dict_file)
        self.predicate_dict = self._load_dict(self.verb_dict_file)
        self.label_dict = self._load_label_dict(self.target_dict_file)
        self._load_anno()

    @staticmethod
    def _open(path):
        return gzip.open(path, "rt") if path.endswith(".gz") \
            else open(path)

    def _load_dict(self, path):
        d = {}
        with self._open(path) as f:
            for i, line in enumerate(f):
                d[line.strip()] = i
        return d

    def _load_label_dict(self, path):
        d = {}
        index = 0
        with self._open(path) as f:
            for line in f:
                label = line.strip()
                if label.startswith("B-"):
                    d[label] = index
                    d["I-" + label[2:]] = index + 1
                    index += 2
                elif label == "O":
                    d[label] = index
                    index += 1
        return d

    def _load_anno(self):
        self.sentences = []
        self.predicates = []
        self.labels = []
        with tarfile.open(self.data_file) as tf:
            wordfile = [m.name for m in tf
                        if m.name.endswith("words.gz")
                        or m.name.endswith("words")][0]
            propfile = [m.name for m in tf
                        if m.name.endswith("props.gz")
                        or m.name.endswith("props")][0]

            def lines(name):
                f = tf.extractfile(name)
                data = f.read()
                if name.endswith(".gz"):
                    data = gzip.decompress(data)
                return data.decode().splitlines()

            sentences = []
            labels = []
            one_seg = []
            for word_line, prop_line in zip(lines(wordfile),
                                            lines(propfile)):
                word = word_line.strip()
                label = prop_line.strip().split()
                if len(label) == 0:          # sentence boundary
                    if len(one_seg) > 0:
                        self._parse_sentence(one_seg, sentences, labels)
                    one_seg = []
                else:
                    one_seg.append((word, label))
            if one_seg:
                self._parse_sentence(one_seg, sentences, labels)

    def _parse_sentence(self, seg, sentences, labels):
        words = [w for w, _ in seg]
        n_pred = len(seg[0][1]) - 1
        for p in range(n_pred):
            # column p+1 holds the BIO chunks for predicate p
            tags = []
            verb = None
            cur = None
            for w, cols in seg:
                chunk = cols[p + 1]
                if chunk.startswith("("):
                    cur = chunk[1:].split("*")[0]
                    tags.append("B-" + cur)
                    if cur == "V":
                        verb = w
                elif cur is not None:
                    tags.append("I-" + cur)
                else:
                    tags.append("O")
                if chunk.endswith(")"):
                    cur = None
            if verb is None:
                continue
            self.sentences.append(words)
            self.predicates.append(verb)
            self.labels.append(tags)

    def get_dict(self):
        """Parity: Conll05st.get_dict."""
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        predicate = self.predicates[idx]
        labels = self.labels[idx]
        sen_len = len(sentence)
        verb_index = labels.index("B-V")
        mark = [0] * len(labels)
        if verb_index > 0:
            mark[verb_index - 1] = 1
            ctx_n1 = sentence[verb_index - 1]
        else:
            ctx_n1 = "bos"
        if verb_index > 1:
            mark[verb_index - 2] = 1
            ctx_n2 = sentence[verb_index - 2]
        else:
            ctx_n2 = "bos"
        mark[verb_index] = 1
        ctx_0 = sentence[verb_index]
        if verb_index < len(labels) - 1:
            mark[verb_index + 1] = 1
            ctx_p1 = sentence[verb_index + 1]
        else:
            ctx_p1 = "eos"
        if verb_index < len(labels) - 2:
            mark[verb_index + 2] = 1
            ctx_p2 = sentence[verb_index + 2]
        else:
            ctx_p2 = "eos"
        word_idx = [self.word_dict.get(w, UNK_IDX) for w in sentence]
        ctx_n2_idx = [self.word_dict.get(ctx_n2, UNK_IDX)] * sen_len
        ctx_n1_idx = [self.word_dict.get(ctx_n1, UNK_IDX)] * sen_len
        ctx_0_idx = [self.word_dict.get(ctx_0, UNK_IDX)] * sen_len
        ctx_p1_idx = [self.word_dict.get(ctx_p1, UNK_IDX)] * sen_len
        ctx_p2_idx = [self.word_dict.get(ctx_p2, UNK_IDX)] * sen_len
        pred_idx = [self.predicate_dict.get(predicate)] * sen_len
        label_idx = [self.label_dict.get(l) for l in labels]
        return (np.array(word_idx), np.array(ctx_n2_idx),
                np.array(ctx_n1_idx), np.array(ctx_0_idx),
                np.array(ctx_p1_idx), np.array(ctx_p2_idx),
                np.array(pred_idx), np.array(mark),
                np.array(label_idx))

    def __len__(self):
        return len(self.sentences)
