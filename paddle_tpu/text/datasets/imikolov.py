"""Parity: python/paddle/text/datasets/imikolov.py — PTB language-model
dataset over simple-examples.tgz (ptb.train.txt / ptb.valid.txt)."""
from __future__ import annotations

import collections
import tarfile

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []


class Imikolov(Dataset):
    """Parity: paddle.text.Imikolov(data_file, data_type, window_size,
    mode, min_word_freq)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        assert data_type.upper() in ("NGRAM", "SEQ")
        assert mode in ("train", "test")
        if data_type.upper() == "NGRAM":
            assert window_size > 0
        self.data_file = _require(data_file)
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.mode = mode
        self.min_word_freq = min_word_freq
        self.word_idx = self._build_dict()
        self._load_anno()

    def _word_count(self, f, word_freq=None):
        if word_freq is None:
            word_freq = collections.defaultdict(int)
        for line in f:
            words = line.decode().strip().split()
            for w in words:
                word_freq[w] += 1
            word_freq["<s>"] += 1
            word_freq["<e>"] += 1
        return word_freq

    def _build_dict(self):
        train_name = "./simple-examples/data/ptb.train.txt"
        test_name = "./simple-examples/data/ptb.valid.txt"
        with tarfile.open(self.data_file) as tf:
            word_freq = self._word_count(
                tf.extractfile(test_name),
                self._word_count(tf.extractfile(train_name)))
            word_freq.pop("<unk>", None)
            word_freq = [x for x in word_freq.items()
                         if x[1] >= self.min_word_freq]
            word_freq_sorted = sorted(word_freq,
                                      key=lambda x: (-x[1], x[0]))
            words, _ = list(zip(*word_freq_sorted)) \
                if word_freq_sorted else ((), ())
            word_idx = dict(zip(words, range(len(words))))
            word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        self.data = []
        fname = "./simple-examples/data/ptb.{}.txt".format(
            "train" if self.mode == "train" else "valid")
        unk = self.word_idx["<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(fname)
            for line in f:
                if self.data_type == "NGRAM":
                    words = ["<s>"] + line.decode().strip().split() \
                        + ["<e>"]
                    ids = [self.word_idx.get(w, unk) for w in words]
                    if len(ids) >= self.window_size:
                        for i in range(self.window_size, len(ids) + 1):
                            self.data.append(
                                tuple(ids[i - self.window_size:i]))
                else:
                    words = line.decode().strip().split()
                    ids = [self.word_idx.get(w, unk) for w in words]
                    src = [self.word_idx["<s>"]] + ids
                    trg = ids + [self.word_idx["<e>"]]
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx]) \
            if self.data_type == "SEQ" else np.array(self.data[idx])

    def __len__(self):
        return len(self.data)
