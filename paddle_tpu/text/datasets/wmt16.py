"""Parity: python/paddle/text/datasets/wmt16.py — WMT16 en-de over the
wmt16.tar.gz layout (wmt16/{train,val,test} with tab-separated
bitext); dictionaries built from the train split with <s>/<e>/<unk>
heads, cached next to the archive."""
from __future__ import annotations

import os
import tarfile
from collections import defaultdict

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []

START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"
TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220


class WMT16(Dataset):
    """Parity: paddle.text.WMT16(data_file, mode, src_dict_size,
    trg_dict_size, lang)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=True):
        assert mode in ("train", "test", "val")
        self.data_file = _require(data_file)
        self.mode = mode
        self.lang = lang
        assert src_dict_size > 0 and trg_dict_size > 0, \
            "dict_size should be set as positive number"
        self.src_dict_size = min(
            src_dict_size,
            TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS)
        self.trg_dict_size = min(
            trg_dict_size,
            TOTAL_DE_WORDS if lang == "en" else TOTAL_EN_WORDS)
        self.src_dict = self._load_dict(lang, self.src_dict_size)
        self.trg_dict = self._load_dict(
            "de" if lang == "en" else "en", self.trg_dict_size)
        self._load_data()

    def _dict_path(self, lang, dict_size):
        return os.path.join(os.path.dirname(self.data_file),
                            f"wmt16_{lang}_{dict_size}.dict")

    def _load_dict(self, lang, dict_size):
        path = self._dict_path(lang, dict_size)
        found = os.path.exists(path) and \
            len(open(path, "rb").readlines()) == dict_size
        if not found:
            self._build_dict(path, dict_size, lang)
        word_dict = {}
        with open(path, "rb") as f:
            for idx, line in enumerate(f):
                word_dict[line.strip().decode()] = idx
        return word_dict

    def _build_dict(self, path, dict_size, lang):
        word_freq = defaultdict(int)
        col = 0 if lang == "en" else 1
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    word_freq[w] += 1
        with open(path, "wb") as fout:
            fout.write(
                f"{START_MARK}\n{END_MARK}\n{UNK_MARK}\n".encode())
            for idx, (word, _) in enumerate(sorted(
                    word_freq.items(), key=lambda x: x[1],
                    reverse=True)):
                if idx + 3 == dict_size:
                    break
                fout.write(word.encode() + b"\n")

    def _load_data(self):
        start_id = self.src_dict[START_MARK]
        end_id = self.src_dict[END_MARK]
        unk_id = self.src_dict[UNK_MARK]
        src_col = 0 if self.lang == "en" else 1
        trg_col = 1 - src_col
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as f:
            for line in f.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [
                    self.src_dict.get(w, unk_id)
                    for w in parts[src_col].split()] + [end_id]
                trg_ids = [self.trg_dict.get(w, unk_id)
                           for w in parts[trg_col].split()]
                self.src_ids.append(src_ids)
                self.trg_ids.append([start_id] + trg_ids)
                self.trg_ids_next.append(trg_ids + [end_id])

    def get_dict(self, lang, reverse=False):
        """Parity: WMT16.get_dict."""
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else dict(d)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]),
                np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)
