"""Parity: python/paddle/text/datasets/uci_housing.py — Boston housing
regression over the whitespace-separated housing.data file."""
from __future__ import annotations

import numpy as np

from ...io import Dataset
from .imdb import _require

__all__ = []

FEATURE_NAMES = ["CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
                 "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT",
                 "convert"]


class UCIHousing(Dataset):
    """Parity: paddle.text.UCIHousing(data_file, mode) — features
    min-max normalized by the training statistics, 80/20 split."""

    def __init__(self, data_file=None, mode="train", download=True):
        assert mode in ("train", "test")
        self.data_file = _require(data_file)
        self.mode = mode
        self.dtype = "float32"
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins = data.max(axis=0), data.min(axis=0)
        avgs = data.sum(axis=0) / data.shape[0]
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" \
            else data[offset:]

    def __getitem__(self, idx):
        d = self.data[idx]
        return (np.array(d[:-1]).astype(self.dtype),
                np.array(d[-1:]).astype(self.dtype))

    def __len__(self):
        return len(self.data)
