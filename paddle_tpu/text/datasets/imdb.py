"""Parity: python/paddle/text/datasets/imdb.py — IMDB sentiment over
the aclImdb_v1.tar.gz layout (train|test)/(pos|neg)/*.txt."""
from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ...io import Dataset

__all__ = []


def _require(data_file):
    if data_file is None:
        raise RuntimeError(
            "no network egress in this environment: pass data_file="
            "<path to aclImdb_v1.tar.gz> (reference layout)")
    return data_file


class Imdb(Dataset):
    """Parity: paddle.text.Imdb(data_file, mode, cutoff) — docs are
    id-lists over a frequency-sorted word dict (built from train+test
    like the reference), labels 0=pos 1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        assert mode in ("train", "test")
        self.data_file = _require(data_file)
        self.mode = mode
        self.word_idx = self._build_work_dict(cutoff)
        self._load_anno()

    def _tokenize(self, text):
        pat = re.compile(r"[^a-z\s]")
        return pat.sub("", text.decode("latin-1").lower()).split()

    def _build_work_dict(self, cutoff):
        word_freq = collections.defaultdict(int)
        pattern = re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        with tarfile.open(self.data_file) as tf:
            for member in tf.getmembers():
                if pattern.match(member.name):
                    for w in self._tokenize(
                            tf.extractfile(member).read()):
                        word_freq[w] += 1
        word_freq.pop("<unk>", None)
        freq = [x for x in word_freq.items() if x[1] > cutoff]
        sorted_freq = sorted(freq, key=lambda x: (-x[1], x[0]))
        words, _ = list(zip(*sorted_freq)) if sorted_freq else ((), ())
        word_idx = dict(zip(words, range(len(words))))
        word_idx["<unk>"] = len(words)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, pat in ((0, rf"aclImdb/{self.mode}/pos/.*\.txt$"),
                           (1, rf"aclImdb/{self.mode}/neg/.*\.txt$")):
            pattern = re.compile(pat)
            with tarfile.open(self.data_file) as tf:
                for member in tf.getmembers():
                    if pattern.match(member.name):
                        doc = self._tokenize(
                            tf.extractfile(member).read())
                        self.docs.append(
                            [self.word_idx.get(w, unk) for w in doc])
                        self.labels.append(label)

    def __getitem__(self, idx):
        return (np.array(self.docs[idx]),
                np.array([self.labels[idx]]))

    def __len__(self):
        return len(self.docs)
