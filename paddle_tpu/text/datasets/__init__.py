"""paddle.text datasets (parity: python/paddle/text/datasets/ —
Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16).

No network egress in this environment: every dataset takes the
``data_file`` path the reference would have downloaded (same archive
format, parsed identically); item structures/dtypes match the
reference's ``__getitem__``.
"""
from .imdb import Imdb
from .imikolov import Imikolov
from .movielens import Movielens
from .uci_housing import UCIHousing
from .wmt14 import WMT14
from .wmt16 import WMT16
from .conll05 import Conll05st

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]
