"""paddle.text (parity: python/paddle/text/ — viterbi_decode.py
ViterbiDecoder/viterbi_decode; datasets are archive-file-backed here,
the reference downloads them).
"""
from .viterbi_decode import viterbi_decode, ViterbiDecoder
from .datasets import (Conll05st, Imdb, Imikolov, Movielens,
                       UCIHousing, WMT14, WMT16)
from . import datasets

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "viterbi_decode", "ViterbiDecoder"]
