"""paddle_tpu.profiler — host + device profiling.

Parity: python/paddle/profiler/profiler.py (reference — Profiler :79 with
scheduler states CLOSED/READY/RECORD/RECORD_AND_RETURN :346, RecordEvent
spans event_tracing.py, chrome-trace export chrometracing_logger.cc,
summary statistics profiler_statistic.py).

TPU-native design: the two-tier model is kept — host spans are recorded
by ``RecordEvent`` (and automatically for every dispatched op while a
profiler is recording), device activity comes from ``jax.profiler``
(XPlane traces, TensorBoard-consumable) started/stopped by the same
scheduler.  ``export_chrome_tracing`` writes the host timeline as a
standard chrome://tracing JSON; ``summary()`` prints the reference-style
aggregated table.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result"]


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1          # accepted for API parity; maps to the device trace
    TPU = 2
    CUSTOM_DEVICE = 3


class _HostEvent:
    __slots__ = ("name", "start", "end", "tid", "event_type")

    def __init__(self, name, start, end, tid, event_type="UserDefined"):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid
        self.event_type = event_type


# active profilers (RecordEvent + op dispatch feed these)
_ACTIVE: List["Profiler"] = []
_LOCK = threading.Lock()


def _record(name: str, start: float, end: float, event_type: str):
    if not _ACTIVE:
        return
    ev = _HostEvent(name, start, end, threading.get_ident(), event_type)
    with _LOCK:
        for p in _ACTIVE:
            p._events.append(ev)


class RecordEvent:
    """Host span (parity: paddle.profiler.RecordEvent,
    python/paddle/profiler/utils.py:33).  Usable as context manager or
    begin()/end() pair; also emits a jax named scope into the device
    trace."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start = None
        self._jax_ctx = None

    def begin(self):
        self._start = time.perf_counter()
        try:
            import jax
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None

    def end(self):
        if self._start is None:
            return
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(None, None, None)
        _record(self.name, self._start, time.perf_counter(),
                self.event_type)
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


def is_profiling() -> bool:
    return bool(_ACTIVE)


def _sync_dispatch_hook():
    """Install/remove the per-op span recorder in the eager dispatch choke
    point (the analog of the reference's kernel-level RecordEvent in
    phi kernels)."""
    from ..core import dispatch as _dispatch
    _dispatch._op_profile_hook[0] = _record if _ACTIVE else None


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """Parity: paddle.profiler.make_scheduler (profiler.py:120) — cycle
    CLOSED*closed -> READY*ready -> RECORD*(record-1) ->
    RECORD_AND_RETURN, repeating ``repeat`` times (0 = forever)."""
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str, worker_name: str = None):
    """Parity: paddle.profiler.export_chrome_tracing — returns an
    on_trace_ready callback writing chrome://tracing JSON."""
    def handler(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = worker_name or f"paddle_tpu_{os.getpid()}"
        path = os.path.join(dir_name, f"{fname}_{prof._round}.json")
        prof._export_chrome(path)
        return path
    return handler


def load_profiler_result(path: str):
    if path.endswith(".pb"):
        from ..onnx.proto import decode
        with open(path, "rb") as f:
            fields = decode(f.read())
        return json.loads(fields[2][0].decode())
    with open(path) as f:
        return json.load(f)


class Profiler:
    """Parity: paddle.profiler.Profiler (profiler.py:79)."""

    def __init__(self, *, targets: Sequence[ProfilerTarget] = None,
                 scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False,
                 with_flops=False, emit_nvtx=False):
        if isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                       repeat=1)
        self._scheduler = scheduler or _default_scheduler
        self._on_trace_ready = on_trace_ready
        self._targets = list(targets or [ProfilerTarget.CPU])
        self._timer_only = timer_only
        self._events: List[_HostEvent] = []
        self._last_round_events: List[_HostEvent] = []
        self._step_num = 0
        self._round = 0
        self._state = ProfilerState.CLOSED
        self._device_tracing = False
        self._trace_dir = None
        self._step_rec: Optional[RecordEvent] = None
        self._last_path = None

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        self._state = self._scheduler(self._step_num)
        self._apply_state()
        self._begin_step_span()

    def stop(self):
        self._end_step_span()
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._finish_round()
        self._close_recording()
        self._state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        self._end_step_span()
        prev = self._state
        self._step_num += 1
        self._state = self._scheduler(self._step_num)
        if prev == ProfilerState.RECORD_AND_RETURN:
            self._finish_round()
        self._apply_state(prev)
        self._begin_step_span()

    # -- internals -----------------------------------------------------------
    def _begin_step_span(self):
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._step_rec = RecordEvent(
                f"ProfileStep#{self._step_num}", "ProfileStep")
            self._step_rec.begin()

    def _end_step_span(self):
        if self._step_rec is not None:
            self._step_rec.end()
            self._step_rec = None

    def _apply_state(self, prev=None):
        recording = self._state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        was = self in _ACTIVE
        if recording and not was:
            with _LOCK:
                _ACTIVE.append(self)
            _sync_dispatch_hook()
            self._start_device_trace()
        elif not recording and was:
            self._close_recording()

    def _close_recording(self):
        if self in _ACTIVE:
            with _LOCK:
                _ACTIVE.remove(self)
        _sync_dispatch_hook()
        self._stop_device_trace()

    def _start_device_trace(self):
        if self._timer_only or self._device_tracing:
            return
        try:
            import jax
            self._trace_dir = self._trace_dir or \
                os.path.join("/tmp", f"pt_prof_{os.getpid()}")
            jax.profiler.start_trace(self._trace_dir)
            self._device_tracing = True
        except Exception:
            self._device_tracing = False

    def _stop_device_trace(self):
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def _finish_round(self):
        self._stop_device_trace()
        if self._on_trace_ready is not None:
            self._last_path = self._on_trace_ready(self)
        self._round += 1
        # each scheduler round is an independent profile window; keep the
        # finished round readable via .events / summary() after stop()
        self._last_round_events = self._events
        self._events = []

    # -- results -------------------------------------------------------------
    @property
    def events(self) -> List[_HostEvent]:
        return list(self._events or self._last_round_events)

    def _export_chrome(self, path: str):
        """Valid chrome://tracing JSON from the host spans ALONE when no
        device trace exists (device-less CPU runs, timer_only) — plus
        the runtime span log (step markers, checkpoint writes, comm
        timeouts) and the jax device trace folded in when present.
        ``load_profiler_result`` round-trips the output."""
        from ..observability.trace_merge import (merge_chrome_trace,
                                                 span_log)
        events = self._events or self._last_round_events
        trace_dir = self._trace_dir if not self._timer_only else None
        # only runtime spans overlapping this profile window: the span
        # log is process-lived, the profiler round is not
        t_lo = min((e.start for e in events), default=None)
        if t_lo is None:
            # a round with no host spans has no window to clip to —
            # exporting the whole process-lived span log instead would
            # dump unrelated history
            runtime = []
        else:
            t_hi = max(e.end for e in events)
            runtime = [ev for ev in span_log.events()
                       if ev[4] >= t_lo and ev[3] <= t_hi]
        return merge_chrome_trace(path, host_events=events,
                                  runtime_events=runtime,
                                  device_trace_dir=trace_dir)

    def export(self, path: str, format: str = "json"):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        return self._export_chrome(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Aggregated event table (parity: profiler_statistic.py
        summary)."""
        agg: Dict[str, List[float]] = {}
        for e in (self._events or self._last_round_events):
            agg.setdefault(e.name, []).append(e.end - e.start)
        scale = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        rows = sorted(((n, len(d), sum(d) * scale,
                        sum(d) / len(d) * scale, max(d) * scale)
                       for n, d in agg.items()),
                      key=lambda r: -r[2])
        lines = [f"{'Name':<44} {'Calls':>6} {'Total(' + time_unit + ')':>12} "
                 f"{'Avg':>10} {'Max':>10}",
                 "-" * 86]
        lines += [f"{n[:44]:<44} {c:>6} {t:>12.3f} {a:>10.3f} {m:>10.3f}"
                  for n, c, t, a, m in rows]
        table = "\n".join(lines)
        print(table)
        return table

    # -- context manager -----------------------------------------------------
    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


class SortedKeys(enum.Enum):
    """Summary-table sort keys (parity: profiler_statistic.py SortedKeys;
    the GPU* keys order by device-span time here — TPU device spans)."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView(enum.Enum):
    """Summary views (parity: profiler.py SummaryView)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name: str, worker_name: str = None):
    """Parity: paddle.profiler.export_protobuf — an on_trace_ready
    callback writing a protobuf file.  Payload schema (proto wire
    format, written with the in-tree writer): field 1 = version string,
    field 2 = chrome-trace JSON bytes; ``load_profiler_result`` on the
    .pb path round-trips it."""
    def handler(prof: "Profiler"):
        from ..onnx.proto import fs, fb
        os.makedirs(dir_name, exist_ok=True)
        fname = worker_name or f"paddle_tpu_{os.getpid()}"
        path = os.path.join(dir_name, f"{fname}_{prof._round}.pb")
        tmp_json = path + ".json.tmp"
        prof._export_chrome(tmp_json)
        with open(tmp_json, "rb") as f:
            payload = f.read()
        os.remove(tmp_json)
        with open(path, "wb") as f:
            f.write(fs(1, "paddle_tpu-profiler-v1") + fb(2, payload))
        prof._last_path = path
        return path
    return handler


__all__ += ["SortedKeys", "SummaryView", "export_protobuf"]
