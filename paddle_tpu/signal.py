"""paddle_tpu.signal — frame / overlap_add / stft / istft.

Parity: python/paddle/signal.py (reference; frame & overlap_add kernels
paddle/phi/kernels/cpu/frame_kernel.cc, overlap_add_kernel.cc).  All four
lower to gather/scatter + XLA FFT, differentiable end to end.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .core.dispatch import apply_op
from .core.tensor import Tensor
from .ops._helpers import as_value, wrap, targ

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slide windows of ``frame_length`` every ``hop_length`` (reference
    python/paddle/signal.py:30)."""
    from .ops._helpers import sliding_windows

    def fn(v):
        ax = axis % v.ndim
        out = sliding_windows(v, ax, frame_length, hop_length)
        # paddle layout: frame_length before num_frames when axis=-1
        if axis in (-1, v.ndim - 1):
            out = jnp.swapaxes(out, ax, ax + 1)
        return out
    return apply_op("frame", fn, (x,))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference python/paddle/signal.py:176)."""
    def fn(v):
        if axis in (-1, v.ndim - 1):
            frame_length, n = v.shape[-2], v.shape[-1]
            frames = jnp.swapaxes(v, -1, -2)   # [..., n, frame_length]
        else:
            n, frame_length = v.shape[0], v.shape[1]
            frames = jnp.moveaxis(v, (0, 1), (-2, -1))
        out_len = (n - 1) * hop_length + frame_length
        starts = jnp.arange(n) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = jnp.zeros(frames.shape[:-2] + (out_len,), v.dtype)
        out = out.at[..., idx.reshape(-1)].add(
            frames.reshape(frames.shape[:-2] + (-1,)))
        if axis not in (-1, v.ndim - 1):
            out = jnp.moveaxis(out, -1, 0)
        return out
    return apply_op("overlap_add", fn, (x,))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (parity: paddle.signal.stft).

    x: [batch, seq] (or [seq]); returns [batch, n_fft//2+1, frames]
    complex (onesided) like the reference.
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    wv = targ(window) if window is not None else None

    def fn(v, *rest):
        squeeze = v.ndim == 1
        if squeeze:
            v = v[None]
        if rest:
            w = rest[0]
            pad = (n_fft - win_length) // 2
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        else:
            w = jnp.ones((n_fft,), v.dtype)
        if center:
            v = jnp.pad(v, ((0, 0), (n_fft // 2, n_fft // 2)),
                        mode=pad_mode)
        n = (v.shape[-1] - n_fft) // hop_length + 1
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :])
        frames = v[:, idx]                       # [B, n, n_fft]
        frames = frames * w[None, None, :]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        out = jnp.swapaxes(spec, -1, -2)         # [B, freq, frames]
        return out[0] if squeeze else out

    args = (x,) if wv is None else (x, wv)
    return apply_op("stft", fn, args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-sum normalization (parity:
    paddle.signal.istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    wv = targ(window) if window is not None else None

    def fn(v, *rest):
        squeeze = v.ndim == 2
        if squeeze:
            v = v[None]
        if rest:
            w = rest[0]
            pad = (n_fft - win_length) // 2
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        else:
            w = jnp.ones((n_fft,), jnp.float32)
        spec = jnp.swapaxes(v, -1, -2)           # [B, frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.fft.ifft(spec, axis=-1)
        if not return_complex:
            frames = jnp.real(frames)
        frames = frames * w[None, None, :]
        n = frames.shape[1]
        out_len = (n - 1) * hop_length + n_fft
        idx = (jnp.arange(n)[:, None] * hop_length
               + jnp.arange(n_fft)[None, :]).reshape(-1)
        out = jnp.zeros((frames.shape[0], out_len), frames.dtype)
        out = out.at[:, idx].add(frames.reshape(frames.shape[0], -1))
        wsum = jnp.zeros((out_len,), w.dtype)
        wsum = wsum.at[idx].add(jnp.tile(w * w, (n,)))
        out = out / jnp.where(wsum > 1e-11, wsum, 1.0)
        if center:
            out = out[:, n_fft // 2:]
            tail = out.shape[-1] - (n_fft // 2)
            out = out[:, :tail] if length is None else out[:, :length]
        elif length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out

    args = (x,) if wv is None else (x, wv)
    return apply_op("istft", fn, args)
