"""Serialization: paddle.save / paddle.load.

Parity: python/paddle/framework/io.py:721,960 (reference) — pickled nested
state structures with tensors serialized as numpy arrays (bfloat16 kept via
ml_dtypes view round-trip).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

import jax.numpy as jnp

from .core.tensor import Tensor


class _TensorPayload:
    """Pickle-stable tensor container (bfloat16-safe)."""

    def __init__(self, array: np.ndarray, stop_gradient: bool = True):
        self.dtype_name = array.dtype.name if array.dtype.names is None \
            else str(array.dtype)
        if array.dtype == jnp.bfloat16:
            self.dtype_name = "bfloat16"
            self.data = array.view(np.uint16)
        else:
            self.data = array
        self.stop_gradient = stop_gradient

    def to_tensor(self) -> Tensor:
        arr = self.data
        if self.dtype_name == "bfloat16":
            arr = arr.view(jnp.bfloat16)
        t = Tensor(arr)
        t.stop_gradient = self.stop_gradient
        return t


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._value), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        t = obj.to_tensor()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        un = [_unpack(v, return_numpy) for v in obj]
        return un if isinstance(obj, list) else tuple(un)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """paddle.save parity (reference python/paddle/framework/io.py:721)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    """paddle.load parity (reference python/paddle/framework/io.py:960)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
