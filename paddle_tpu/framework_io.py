"""Serialization: paddle.save / paddle.load.

Parity: python/paddle/framework/io.py:721,960 (reference) — pickled nested
state structures containing only stdlib/numpy types.  A Tensor is stored as
a small marker dict holding a plain ndarray (uint16 view for bfloat16) plus
its stop_gradient flag, so any numpy-capable reader can open the file and
reference-produced pickles of plain ndarrays load here unchanged (and stay
ndarrays, like the reference's load does).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

import jax.numpy as jnp

from .core.tensor import Tensor

_TENSOR_KEY = "__paddle_tpu_tensor__"


class _TensorPayload:
    """Backward-compat unpickler for round-1 checkpoints only (new files
    never contain this class)."""

    def __setstate__(self, state):
        self.__dict__.update(state)

    def to_tensor(self) -> Tensor:
        arr = self.data
        if self.dtype_name == "bfloat16":
            arr = arr.view(jnp.bfloat16)
        t = Tensor(arr)
        t.stop_gradient = getattr(self, "stop_gradient", True)
        return t


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._value)
        rec = {_TENSOR_KEY: True, "stop_gradient": bool(obj.stop_gradient),
               "bf16": False, "data": arr}
        if arr.dtype == jnp.bfloat16:
            rec["bf16"] = True
            rec["data"] = arr.view(np.uint16)
        return rec
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        t = obj.to_tensor()
        return t.numpy() if return_numpy else t
    if isinstance(obj, dict) and obj.get(_TENSOR_KEY):
        arr = obj["data"]
        if obj.get("bf16"):
            arr = arr.view(jnp.bfloat16)
        if return_numpy:
            return arr
        t = Tensor(arr)
        t.stop_gradient = obj.get("stop_gradient", True)
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        un = [_unpack(v, return_numpy) for v in obj]
        return un if isinstance(obj, list) else tuple(un)
    return obj


def _atomic_pickle(payload: Any, path: str, protocol: int = 4,
                   max_tries: int = 3, backoff_s: float = 0.05):
    """Pickle ``payload`` to ``path`` via temp file + ``os.replace`` —
    a crash or injected failure at any instant leaves either the old
    file or the new one, never a truncated pickle.  Transient I/O errors
    retry with exponential backoff (flaky network filesystems under
    checkpoint pressure are the norm, not the exception)."""
    from .testing.faults import fault_point
    tmp = f"{path}.tmp.{os.getpid()}"
    last = None
    for attempt in range(max_tries):
        try:
            fault_point("io.save")
            with open(tmp, "wb") as f:
                pickle.dump(payload, f, protocol=protocol)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return
        except OSError as e:
            last = e
            if attempt < max_tries - 1:
                import time
                time.sleep(backoff_s * (2 ** attempt))
        except BaseException:
            # non-I/O failure (unpicklable object, interrupt): no point
            # retrying, but never leave the temp file behind
            _remove_quiet(tmp)
            raise
    _remove_quiet(tmp)
    raise last


def _remove_quiet(path):
    try:
        os.remove(path)
    except OSError:
        pass


def save(obj: Any, path: str, protocol: int = 4, **configs):
    """paddle.save parity (reference python/paddle/framework/io.py:721).

    Crash-safe: written through :func:`_atomic_pickle`, so an
    interrupted save can never leave a truncated ``.pdparams`` where a
    good one (or nothing) used to be."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    _atomic_pickle(_pack(obj), path, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    """paddle.load parity (reference python/paddle/framework/io.py:960)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
