"""paddle.onnx (parity: python/paddle/onnx/export.py — export() via the
external paddle2onnx package).

This environment has no network egress and no onnx wheel baked in, so
export() emits the portable StableHLO artifact via jit.save (loadable by
any StableHLO consumer, including ONNX converters offline) and raises a
clear error for a true .onnx file unless the `onnx` package is present.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Parity: paddle.onnx.export(layer, path, input_spec)."""
    try:
        import onnx  # noqa: F401
        have_onnx = True
    except ImportError:
        have_onnx = False
    # always produce the portable StableHLO artifact, onnx installed or not
    from .. import jit as jit_mod
    jit_mod.save(layer, path, input_spec=input_spec, **configs)
    if not have_onnx:
        raise RuntimeError(
            "the 'onnx' package is not installed in this environment "
            "(no network egress). The model has been exported as a "
            f"portable StableHLO module at '{path}.pdexec' instead — "
            "convert it to ONNX offline, or install onnx to enable "
            "direct export.")
    raise NotImplementedError(
        "direct ONNX serialization is not implemented; the model has been "
        f"exported as a portable StableHLO module at '{path}.pdexec' — "
        "use that as the interchange format")
