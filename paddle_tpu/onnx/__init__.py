"""paddle.onnx (parity: python/paddle/onnx/export.py — reference
export() delegates to the external paddle2onnx package over the
ProgramDesc).

TPU-native: export() captures the layer as a static Program (the same
trace-by-execution capture the Executor compiles) and serializes it to a
real ``.onnx`` ModelProto with the in-tree protobuf writer
(:mod:`.proto` — no external onnx dependency, which this no-egress
environment cannot install).  Ops outside the supported subset raise
with a pointer to the StableHLO export path (jit.save), which covers
everything.
"""
from __future__ import annotations

from typing import List, Optional

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=13, **configs):
    """Parity: paddle.onnx.export(layer, path, input_spec) — writes
    ``<path>.onnx``.  The layer is captured in eval mode (train-mode RNG
    ops are not exportable)."""
    import numpy as np
    from .. import static as static_mod
    from ..core.tensor import Tensor
    from ..jit.api import InputSpec
    from ._convert import program_to_onnx

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        prog = static_mod.Program(name="onnx_export")
        declared = {}
        with static_mod.program_guard(prog):
            feeds = []
            for i, spec in enumerate(input_spec):
                if isinstance(spec, Tensor):
                    shape = list(spec.shape)
                    decl = list(shape)
                    dtype = str(spec.dtype)
                    name = getattr(spec, "name", None) or f"x{i}"
                elif isinstance(spec, InputSpec):
                    decl = [None if (s is None or s < 0) else int(s)
                            for s in spec.shape]
                    shape = [1 if s is None else s for s in decl]
                    dtype = str(spec.dtype)
                    name = spec.name or f"x{i}"
                else:
                    arr = np.asarray(spec)
                    shape, dtype, name = list(arr.shape), str(arr.dtype), \
                        f"x{i}"
                    decl = list(shape)
                declared[name] = decl        # None dims -> dim_param
                feeds.append(static_mod.data(name, shape, dtype))
            out = layer(*feeds)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        data = program_to_onnx(prog, outs, opset=opset_version,
                               declared_shapes=declared)
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    target = path if path.endswith(".onnx") else path + ".onnx"
    with open(target, "wb") as f:
        f.write(data)
    return target
