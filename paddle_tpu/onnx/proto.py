"""Minimal ONNX protobuf writer/reader (no external onnx dependency).

The environment ships no `onnx` wheel, but ONNX files are plain protobuf
— this module hand-encodes the ModelProto subset needed to serialize
captured programs (and decodes it back for verification).  Field numbers
follow onnx.proto3 (onnx/onnx.proto in the ONNX repo).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

# -- protobuf wire primitives ------------------------------------------------


def _varint(n: int) -> bytes:
    n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def fv(field: int, val: int) -> bytes:
    """varint field"""
    return _key(field, 0) + _varint(int(val))


def fb(field: int, data: bytes) -> bytes:
    """length-delimited field"""
    return _key(field, 2) + _varint(len(data)) + data


def fs(field: int, s: str) -> bytes:
    return fb(field, s.encode())


def ff(field: int, val: float) -> bytes:
    """float (fixed32) field"""
    return _key(field, 5) + struct.pack("<f", float(val))


# -- ONNX message builders ---------------------------------------------------
# TensorProto.DataType
FLOAT, INT64, INT32, BOOL = 1, 7, 6, 9
_NP2ONNX = {np.dtype(np.float32): FLOAT, np.dtype(np.int64): INT64,
            np.dtype(np.int32): INT32, np.dtype(np.bool_): BOOL}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    dt = _NP2ONNX.get(arr.dtype)
    if dt is None:
        arr = arr.astype(np.float32)
        dt = FLOAT
    out = b"".join(fv(1, d) for d in arr.shape)
    out += fv(2, dt)
    out += fs(8, name)
    out += fb(9, arr.tobytes())        # raw_data
    return out


def value_info(name: str, elem_type: int, shape: Sequence) -> bytes:
    dims = b""
    for d in shape:
        if isinstance(d, str) or d is None or (isinstance(d, int)
                                               and d < 0):
            dims += fb(1, fs(2, str(d) if d else "N"))   # dim_param
        else:
            dims += fb(1, fv(1, int(d)))                  # dim_value
    tensor_type = fv(1, elem_type) + fb(2, dims)
    return fs(1, name) + fb(2, fb(1, tensor_type))


# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_FLOATS, A_INTS = 1, 2, 3, 4, 6, 7


def attr_int(name: str, val: int) -> bytes:
    return fs(1, name) + fv(3, val) + fv(20, A_INT)


def attr_ints(name: str, vals: Sequence[int]) -> bytes:
    return fs(1, name) + b"".join(fv(8, v) for v in vals) + fv(20, A_INTS)


def attr_float(name: str, val: float) -> bytes:
    return fs(1, name) + ff(2, val) + fv(20, A_FLOAT)


def attr_str(name: str, val: str) -> bytes:
    return fs(1, name) + fb(4, val.encode()) + fv(20, A_STRING)


def node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
         name: str = "", attrs: Sequence[bytes] = ()) -> bytes:
    out = b"".join(fs(1, i) for i in inputs)
    out += b"".join(fs(2, o) for o in outputs)
    out += fs(3, name or op_type)
    out += fs(4, op_type)
    out += b"".join(fb(5, a) for a in attrs)
    return out


def graph(nodes: Sequence[bytes], name: str, inputs: Sequence[bytes],
          outputs: Sequence[bytes], initializers: Sequence[bytes]) -> bytes:
    out = b"".join(fb(1, n) for n in nodes)
    out += fs(2, name)
    out += b"".join(fb(5, t) for t in initializers)
    out += b"".join(fb(11, i) for i in inputs)
    out += b"".join(fb(12, o) for o in outputs)
    return out


def model(graph_bytes: bytes, opset: int = 13,
          producer: str = "paddle_tpu") -> bytes:
    out = fv(1, 8)                      # ir_version 8
    out += fs(2, producer)
    out += fb(7, graph_bytes)
    out += fb(8, fs(1, "") + fv(2, opset))   # opset_import
    return out


# -- generic protobuf reader (for verification / the numpy evaluator) --------
def decode(buf: bytes) -> Dict[int, List]:
    """field -> list of raw values (ints for varint/fixed, bytes for
    length-delimited)."""
    out: Dict[int, List] = {}
    i = 0
    n = len(buf)

    def rv():
        nonlocal i
        shift = 0
        val = 0
        while True:
            b = buf[i]
            i += 1
            val |= (b & 0x7F) << shift
            if not b & 0x80:
                return val
            shift += 7

    while i < n:
        key = rv()
        field, wire = key >> 3, key & 7
        if wire == 0:
            val = rv()
        elif wire == 2:
            ln = rv()
            val = buf[i:i + ln]
            i += ln
        elif wire == 5:
            val = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == 1:
            val = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"wire type {wire}")
        out.setdefault(field, []).append(val)
    return out


def _read_tensor(tbytes: bytes) -> Tuple[str, np.ndarray]:
    f = decode(tbytes)
    dims = f.get(1, [])
    dt = _ONNX2NP[f[2][0]]
    name = f[8][0].decode()
    arr = np.frombuffer(f[9][0], dtype=dt).reshape(dims)
    return name, arr


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _read_attrs(node_fields) -> Dict:
    attrs = {}
    for ab in node_fields.get(5, []):
        a = decode(ab)
        name = a[1][0].decode()
        atype = a.get(20, [0])[0]
        if atype == A_INT:
            attrs[name] = _signed(a[3][0])
        elif atype == A_INTS:
            attrs[name] = [_signed(v) for v in a.get(8, [])]
        elif atype == A_FLOAT:
            attrs[name] = a[2][0]
        elif atype == A_STRING:
            attrs[name] = a[4][0].decode()
    return attrs


def load_model(data: bytes) -> Dict:
    """Parse a .onnx file into {graph_name, nodes, inputs, outputs,
    initializers} for verification."""
    m = decode(data)
    g = decode(m[7][0])
    nodes = []
    for nb in g.get(1, []):
        nf = decode(nb)
        nodes.append({
            "op_type": nf[4][0].decode(),
            "inputs": [x.decode() for x in nf.get(1, [])],
            "outputs": [x.decode() for x in nf.get(2, [])],
            "attrs": _read_attrs(nf),
        })
    inits = dict(_read_tensor(t) for t in g.get(5, []))

    def names(field):
        return [decode(v)[1][0].decode() for v in g.get(field, [])]

    return {"name": g.get(2, [b""])[0].decode(), "nodes": nodes,
            "inputs": names(11), "outputs": names(12),
            "initializers": inits,
            "opset": decode(m[8][0])[2][0] if 8 in m else None}


# -- numpy evaluator for the exported subset (verification) -----------------
def _np_conv2d(x, w, b, strides, pads, dilations, group):
    from jax import lax
    import jax.numpy as jnp
    out = lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), tuple(strides),
        [(pads[0], pads[2]), (pads[1], pads[3])],
        rhs_dilation=tuple(dilations), feature_group_count=group,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    out = np.asarray(out)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


def _np_pool(x, kind, kernel, strides, pads):
    N, C, H, W = x.shape
    ph0, pw0, ph1, pw1 = pads
    fill = -np.inf if kind == "Max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)),
                constant_values=fill)
    # ONNX default count_include_pad=0: average divides by the number of
    # NON-pad elements in each window (what the converter exports)
    mask = np.pad(np.ones((H, W), x.dtype),
                  ((ph0, ph1), (pw0, pw1)), constant_values=0.0)
    kh, kw = kernel
    sh, sw = strides
    oh = (xp.shape[2] - kh) // sh + 1
    ow = (xp.shape[3] - kw) // sw + 1
    out = np.empty((N, C, oh, ow), x.dtype)
    for i in range(oh):
        for j in range(ow):
            win = xp[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if kind == "Max":
                out[:, :, i, j] = win.max((2, 3))
            else:
                cnt = mask[i * sh:i * sh + kh,
                           j * sw:j * sw + kw].sum()
                out[:, :, i, j] = win.sum((2, 3)) / max(cnt, 1.0)
    return out


def evaluate(model_dict: Dict, feeds: Dict[str, np.ndarray]) -> List:
    """Run the parsed model with numpy (reference interpreter for tests)."""
    env = dict(model_dict["initializers"])
    env.update(feeds)
    for nd in model_dict["nodes"]:
        ins = [env[i] if i else None for i in nd["inputs"]]
        op = nd["op_type"]
        a = nd["attrs"]
        if op == "MatMul":
            out = ins[0] @ ins[1]
        elif op == "Add":
            out = ins[0] + ins[1]
        elif op == "Sub":
            out = ins[0] - ins[1]
        elif op == "Mul":
            out = ins[0] * ins[1]
        elif op == "Div":
            out = ins[0] / ins[1]
        elif op == "Relu":
            out = np.maximum(ins[0], 0)
        elif op == "Sigmoid":
            out = 1.0 / (1.0 + np.exp(-ins[0]))
        elif op == "Tanh":
            out = np.tanh(ins[0])
        elif op == "Exp":
            out = np.exp(ins[0])
        elif op == "Sqrt":
            out = np.sqrt(ins[0])
        elif op == "Neg":
            out = -ins[0]
        elif op == "Softmax":
            ax = a.get("axis", -1)
            e = np.exp(ins[0] - ins[0].max(axis=ax, keepdims=True))
            out = e / e.sum(axis=ax, keepdims=True)
        elif op == "Flatten":
            ax = a.get("axis", 1)
            out = ins[0].reshape(
                int(np.prod(ins[0].shape[:ax])) if ax else 1, -1)
        elif op == "Reshape":
            shape = [int(s) for s in ins[1]]
            out = ins[0].reshape(shape)
        elif op == "Transpose":
            out = np.transpose(ins[0], a.get("perm"))
        elif op == "Concat":
            out = np.concatenate(ins, axis=a.get("axis", 0))
        elif op == "Conv":
            out = _np_conv2d(ins[0], ins[1],
                             ins[2] if len(ins) > 2 else None,
                             a.get("strides", [1, 1]),
                             a.get("pads", [0, 0, 0, 0]),
                             a.get("dilations", [1, 1]),
                             a.get("group", 1))
        elif op in ("MaxPool", "AveragePool"):
            out = _np_pool(ins[0], "Max" if op == "MaxPool" else "Avg",
                           a.get("kernel_shape"),
                           a.get("strides", [1, 1]),
                           a.get("pads", [0, 0, 0, 0]))
        elif op == "GlobalAveragePool":
            out = ins[0].mean(axis=(2, 3), keepdims=True)
        elif op == "Identity":
            out = ins[0]
        elif op == "Log":
            out = np.log(ins[0])
        elif op == "Abs":
            out = np.abs(ins[0])
        elif op == "Floor":
            out = np.floor(ins[0])
        elif op == "Ceil":
            out = np.ceil(ins[0])
        elif op == "Sin":
            out = np.sin(ins[0])
        elif op == "Cos":
            out = np.cos(ins[0])
        elif op == "Expand":
            out = np.broadcast_to(ins[0],
                                  tuple(int(s) for s in ins[1]))
        elif op == "ReduceMean":
            axes = tuple(a.get("axes", [-1]))
            out = ins[0].mean(axis=axes,
                              keepdims=bool(a.get("keepdims", 1)))
        elif op == "Slice":
            data = ins[0]
            sl = [slice(None)] * data.ndim
            for st, en, ax, sp in zip(ins[1], ins[2], ins[3], ins[4]):
                sl[int(ax)] = slice(int(st), int(en), int(sp))
            out = data[tuple(sl)]
        elif op == "Gather":
            out = np.take(ins[0], ins[1], axis=a.get("axis", 0))
        elif op == "Unsqueeze":
            out = ins[0]
            for ax in sorted(int(s) for s in ins[1]):
                out = np.expand_dims(out, ax)
        elif op == "Squeeze":
            out = np.squeeze(ins[0],
                             tuple(int(s) for s in ins[1]))
        elif op == "Erf":
            import math
            out = np.vectorize(math.erf)(ins[0]).astype(ins[0].dtype)
        elif op == "LayerNormalization":
            x = ins[0]
            ax = a.get("axis", -1)
            eps = a.get("epsilon", 1e-5)
            axes = tuple(range(ax % x.ndim, x.ndim))
            m = x.mean(axis=axes, keepdims=True)
            v = x.var(axis=axes, keepdims=True)
            out = (x - m) / np.sqrt(v + eps) * ins[1]
            if len(ins) > 2 and ins[2] is not None:
                out = out + ins[2]
        elif op == "LeakyRelu":
            alpha = a.get("alpha", 0.01)
            out = np.where(ins[0] >= 0, ins[0], alpha * ins[0])
        elif op == "Resize":
            # nearest + integer scales (the exporter's contract).
            # Round rather than truncate: a scale serialized as
            # 1.9999999 is 2, while a genuinely fractional scale is a
            # contract violation and must fail loudly, not floor to a
            # wrong-shaped output.
            scales = []
            for s in ins[2]:
                r = int(round(float(s)))
                if abs(float(s) - r) >= 1e-4:
                    raise ValueError(
                        f"Resize: non-integer scale {float(s)!r} — the "
                        "exporter only emits integer nearest-neighbor "
                        "scales")
                scales.append(r)
            out = ins[0]
            for ax, s in enumerate(scales):
                if s != 1:
                    out = np.repeat(out, s, axis=ax)
        elif op == "BatchNormalization":
            x, scale, bias, mean, var = ins[:5]
            eps = a.get("epsilon", 1e-5)
            shp = [1, -1] + [1] * (x.ndim - 2)
            out = (x - mean.reshape(shp)) / np.sqrt(
                var.reshape(shp) + eps) * scale.reshape(shp) \
                + bias.reshape(shp)
        elif op == "Gemm":
            x, w = ins[0], ins[1]
            if a.get("transB"):
                w = w.T
            out = x @ w
            if len(ins) > 2:
                out = out + ins[2]
        elif op == "Split":
            parts = np.array_split(ins[0], len(nd["outputs"]),
                                   axis=a.get("axis", 0))
            for name, p in zip(nd["outputs"], parts):
                env[name] = np.asarray(p)
            continue
        else:
            raise NotImplementedError(f"evaluator: {op}")
        env[nd["outputs"][0]] = np.asarray(out)
    return [env[o] for o in model_dict["outputs"]]
