"""Captured-program -> ONNX graph conversion.

Parity: python/paddle/onnx/export.py (reference — delegates to the
external paddle2onnx C++ converter over the ProgramDesc).  TPU-native:
the source of truth is the trace-captured Program (the same StatementIR
the Executor compiles); each recorded statement maps to ONNX node(s),
with op attributes recovered from the recorded closures (we own every
closure, so the freevar names are a stable ABI).
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from . import proto as P


def _closure_vars(fn) -> Dict:
    code = getattr(fn, "__code__", None)
    clo = getattr(fn, "__closure__", None)
    if not code or not clo:
        return {}
    out = {}
    for name, cell in zip(code.co_freevars, clo):
        try:
            out[name] = cell.cell_contents
        except ValueError:
            pass
    return out


def _pair(v):
    return list(v) if isinstance(v, (tuple, list)) else [v, v]


class _Converter:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.shapes: Dict[str, tuple] = {}   # name -> shape (inference)
        self.dtypes: Dict[str, np.dtype] = {}  # name -> numpy dtype
        self.min_opset = 13                  # raised by opset-17+ ops
        self._const_n = 0
        self._const_cache: Dict[tuple, str] = {}

    def const(self, arr: np.ndarray, name_hint="const") -> str:
        # content-addressed: per-layer converters bake identical large
        # constants (rope tables, causal masks) — dedup by value so an
        # L-layer model carries ONE copy, not L
        arr = np.asarray(arr)
        key = (name_hint, str(arr.dtype), arr.shape, arr.tobytes())
        cached = self._const_cache.get(key)
        if cached is not None:
            return cached
        self._const_n += 1
        name = f"{name_hint}_{self._const_n}"
        self.initializers.append(P.tensor_proto(name, arr))
        self._const_cache[key] = name
        return name

    def emit(self, op, ins, outs, attrs=()):
        self.nodes.append(P.node(op, ins, outs,
                                 name=f"{op}_{len(self.nodes)}",
                                 attrs=attrs))

    # -- per-op converters ---------------------------------------------------
    def convert(self, stmt, ins: List[str], outs: List[str]):
        cv = _closure_vars(stmt.fn)
        name = stmt.name
        handler = getattr(self, f"_op_{name}", None)
        if handler is None:
            simple = _SIMPLE.get(name)
            if simple is None:
                raise NotImplementedError(
                    f"ONNX export: op '{name}' is not in the supported "
                    f"subset ({sorted(_SIMPLE) + _SPECIAL}); export via "
                    "jit.save (StableHLO) instead")
            self.emit(simple, ins, outs)
            return
        handler(ins, outs, cv, stmt)

    def _op_linear(self, ins, outs, cv, stmt):
        x, w = ins[0], ins[1]
        mm = outs[0] + "_mm" if len(ins) > 2 and ins[2] else outs[0]
        self.emit("MatMul", [x, w], [mm])
        if len(ins) > 2 and ins[2]:
            self.emit("Add", [mm, ins[2]], [outs[0]])

    def _op_matmul(self, ins, outs, cv, stmt):
        tx = cv.get("transpose_x") or cv.get("tx")
        ty = cv.get("transpose_y") or cv.get("ty")
        x, y = ins[0], ins[1]

        def swap_last2(name):
            rank = len(self.shapes.get(name, ()))
            if rank < 2:
                raise NotImplementedError(
                    "ONNX export: matmul transpose of rank<2 operand")
            perm = list(range(rank))
            perm[-1], perm[-2] = perm[-2], perm[-1]
            t = name + "_T"
            self.emit("Transpose", [name], [t],
                      [P.attr_ints("perm", perm)])
            self.shapes[t] = tuple(
                self.shapes[name][p] for p in perm)
            return t

        if tx:
            x = swap_last2(x)
        if ty:
            y = swap_last2(y)
        self.emit("MatMul", [x, y], outs)

    @staticmethod
    def _check_pad(pad, op):
        if isinstance(pad, str):
            raise NotImplementedError(
                f"ONNX export: {op} with '{pad}' padding — use explicit "
                "integer padding, or export via jit.save (StableHLO)")

    def _op_conv2d(self, ins, outs, cv, stmt):
        pad = cv.get("pad", [(0, 0), (0, 0)])
        self._check_pad(pad, "conv2d")
        if cv.get("channel_last"):
            raise NotImplementedError(
                "ONNX export: NHWC conv — export NCHW models")
        strides = _pair(cv.get("strides", (1, 1)))
        dil = _pair(cv.get("dil", (1, 1)))
        attrs = [
            P.attr_ints("strides", strides),
            P.attr_ints("dilations", dil),
            P.attr_ints("pads", [pad[0][0], pad[1][0], pad[0][1],
                                 pad[1][1]]),
            P.attr_int("group", int(cv.get("groups", 1))),
        ]
        self.emit("Conv", ins, outs, attrs)

    def _pool(self, ins, outs, cv, kind):
        pad = cv.get("pad", [(0, 0), (0, 0)])
        self._check_pad(pad, "pool2d")
        if cv.get("ceil_mode"):
            raise NotImplementedError(
                "ONNX export: pool2d ceil_mode=True")
        if cv.get("channel_last"):
            raise NotImplementedError("ONNX export: NHWC pooling")
        if kind == "AveragePool" and not cv.get("exclusive", True):
            raise NotImplementedError(
                "ONNX export: avg_pool2d exclusive=False")
        attrs = [
            P.attr_ints("kernel_shape", _pair(cv.get("k"))),
            P.attr_ints("strides", _pair(cv.get("s", cv.get("k")))),
            P.attr_ints("pads", [pad[0][0], pad[1][0], pad[0][1],
                                 pad[1][1]]),
        ]
        self.emit(kind, ins, outs, attrs)

    def _op_max_pool2d(self, ins, outs, cv, stmt):
        self._pool(ins, outs, cv, "MaxPool")

    def _op_avg_pool2d(self, ins, outs, cv, stmt):
        self._pool(ins, outs, cv, "AveragePool")

    def _op_flatten(self, ins, outs, cv, stmt):
        start = int(cv.get("start_axis", 1))
        stop = cv.get("stop_axis", -1)
        if start == 1 and stop == -1:
            # exactly ONNX Flatten semantics (keep dim0, collapse rest)
            self.emit("Flatten", ins, outs, [P.attr_int("axis", 1)])
            return
        # general flatten keeps ALL leading dims — ONNX Flatten does not;
        # emit a Reshape to the statically-known output shape instead
        out_shape = self.shapes.get(outs[0])
        if out_shape is None:
            raise NotImplementedError(
                "ONNX export: flatten with unknown static shape")
        shp = self.const(np.asarray(list(out_shape), np.int64), "shape")
        self.emit("Reshape", [ins[0], shp], outs)

    def _op_reshape(self, ins, outs, cv, stmt):
        shape = cv.get("shape") or cv.get("shp")
        if shape is None:
            raise NotImplementedError("ONNX export: dynamic reshape")
        shp = self.const(np.asarray(list(shape), np.int64), "shape")
        self.emit("Reshape", [ins[0], shp], outs)

    def _op_transpose(self, ins, outs, cv, stmt):
        perm = cv.get("perm")
        self.emit("Transpose", ins, outs,
                  [P.attr_ints("perm", [int(p) for p in perm])]
                  if perm is not None else ())

    def _sdpa_chain(self, t, q_bhsd, kT_bhds, v_bhsd, outs, dt, S, kS,
                    causal, mask_name=None):
        """Shared scores->softmax->output tail of every attention
        decomposition: inputs are already [B,H,S,D] (q, v) and
        [B,H,D,S] (k); causal masking bakes a bottom-right-aligned
        additive constant.  Writes the final [B,S,H,D] transpose to
        ``outs``."""
        self.emit("MatMul", [q_bhsd, kT_bhds], [f"{t}_s"])
        qshape = self.shapes.get(q_bhsd)
        head_d = int(qshape[-1]) if qshape else None
        if head_d is None:
            raise NotImplementedError(
                "ONNX export: attention needs static head dim")
        scale = self.const(np.asarray(1.0 / np.sqrt(head_d), dt),
                           "scale")
        self.emit("Mul", [f"{t}_s", scale], [f"{t}_ss"])
        cur = f"{t}_ss"
        if mask_name is not None:
            mdt = self.dtypes.get(mask_name)
            if mdt is not None and mdt == np.dtype(bool):
                raise NotImplementedError(
                    "ONNX export: boolean attention mask — pass an "
                    "additive float mask")
            self.emit("Add", [cur, mask_name], [f"{t}_sm"])
            cur = f"{t}_sm"
        if causal:
            m = np.triu(np.full((S, kS), -1e9, np.float32),
                        k=1 + kS - S).astype(dt)
            self.emit("Add", [cur, self.const(m, "causal_mask")],
                      [f"{t}_cm"])
            cur = f"{t}_cm"
        self.emit("Softmax", [cur], [f"{t}_p"],
                  [P.attr_int("axis", -1)])
        self.emit("MatMul", [f"{t}_p", v_bhsd], [f"{t}_o"])
        self.emit("Transpose", [f"{t}_o"], outs,
                  [P.attr_ints("perm", [0, 2, 1, 3])])

    def _op_flash_attention_pallas(self, ins, outs, cv, stmt):
        """Scaled-dot-product attention decomposed to the standard ONNX
        MatMul/Softmax chain (the fused TPU kernel is an execution
        detail, not graph semantics).  Inputs are paddle-layout
        (q, k, v[, additive mask]) in [B, S, H, D]."""
        qs = self.shapes.get(ins[0])
        ks = self.shapes.get(ins[1], qs)
        if qs is None or len(qs) != 4:
            raise NotImplementedError(
                "ONNX export: attention needs a static [B, S, H, D] "
                "query shape")
        S, kS = int(qs[1]), int(ks[1])
        dt = self.dtypes.get(ins[0], np.dtype(np.float32))
        t = outs[0]
        # q/v -> [B,H,S,D]; k fuses both transposes into [B,H,D,S]
        self.emit("Transpose", [ins[0]], [f"{t}_qt"],
                  [P.attr_ints("perm", [0, 2, 1, 3])])
        self.shapes[f"{t}_qt"] = (qs[0], qs[2], qs[1], qs[3])
        self.emit("Transpose", [ins[1]], [f"{t}_kT"],
                  [P.attr_ints("perm", [0, 2, 3, 1])])
        self.emit("Transpose", [ins[2]], [f"{t}_vt"],
                  [P.attr_ints("perm", [0, 2, 1, 3])])
        self._sdpa_chain(t, f"{t}_qt", f"{t}_kT", f"{t}_vt", outs, dt,
                         S, kS, bool(cv.get("is_causal")),
                         mask_name=ins[3] if len(ins) > 3 else None)

    def _op_getitem(self, ins, outs, cv, stmt):
        """Static int/slice indexing -> ONNX Slice (+ Squeeze for int
        axes).  Tensor-valued / bool / newaxis indices fall back to
        jit.save (StableHLO)."""
        if len(ins) != 1:
            raise NotImplementedError(
                "ONNX export: tensor-valued index in getitem")
        template = cv.get("template") or []
        shape = self.shapes.get(ins[0])
        if shape is None:
            raise NotImplementedError(
                "ONNX export: getitem needs a static input shape")
        starts, ends, axes, steps, sq = [], [], [], [], []
        for ax, (kind, payload) in enumerate(template):
            if kind != "static":
                raise NotImplementedError(
                    "ONNX export: tensor index in getitem")
            dim = int(shape[ax])
            if isinstance(payload, slice):
                if payload == slice(None):
                    continue
                sp = 1 if payload.step is None else int(payload.step)
                if sp <= 0:
                    raise NotImplementedError(
                        "ONNX export: negative-step slice")
                # slice.indices applies Python's clamping rules (e.g.
                # x[-7:] on dim 5 starts at 0, not (-7 % 5))
                st, en, sp = payload.indices(dim)
                starts.append(st); ends.append(en)
                axes.append(ax); steps.append(sp)
            elif isinstance(payload, (int, np.integer)) and \
                    not isinstance(payload, (bool, np.bool_)):
                i = int(payload) % dim
                starts.append(i); ends.append(i + 1)
                axes.append(ax); steps.append(1)
                sq.append(ax)
            else:
                raise NotImplementedError(
                    f"ONNX export: getitem index {payload!r}")
        src = ins[0]
        if axes:
            dst = outs[0] + "_sl" if sq else outs[0]
            self.emit("Slice", [
                src,
                self.const(np.asarray(starts, np.int64), "starts"),
                self.const(np.asarray(ends, np.int64), "ends"),
                self.const(np.asarray(axes, np.int64), "axes"),
                self.const(np.asarray(steps, np.int64), "steps")], [dst])
            src = dst
        if sq:
            self.emit("Squeeze",
                      [src, self.const(np.asarray(sq, np.int64),
                                       "axes")], outs)
        elif not axes:
            self.emit("Identity", [src], outs)

    def _op_flash_attention_rope(self, ins, outs, cv, stmt):
        """Rope-fused attention decomposed for ONNX: the neox rotation
        is Slice/Neg/Concat/Mul/Add against baked cos/sin tables (the
        same rope_tables the Pallas kernel consumes), followed by the
        standard MatMul/Softmax chain."""
        from ..ops.pallas_kernels import rope_tables

        qs = self.shapes.get(ins[0])
        if qs is None or len(qs) != 4:
            raise NotImplementedError(
                "ONNX export: rope attention needs a static "
                "[B, S, H, D] query shape")
        S, D = int(qs[1]), int(qs[3])
        dt = self.dtypes.get(ins[0], np.dtype(np.float32))
        # rope_tables takes a float base — int() would silently corrupt
        # rope-scaled fine-tunes with non-integral theta
        cos, sin = rope_tables(S, D, float(cv.get("rotary_base",
                                                  10000.0)))
        cosc = self.const(np.asarray(cos, dt), "rope_cos")
        sinc = self.const(np.asarray(sin, dt), "rope_sin")
        half = D // 2
        t = outs[0]
        perm = [0, 2, 1, 3]

        def i64(vals, hint):
            return self.const(np.asarray(vals, np.int64), hint)

        def rope(src, dst):
            self.emit("Slice", [src, i64([half], "st"), i64([D], "en"),
                                i64([3], "ax"), i64([1], "sp")],
                      [dst + "_h2"])
            self.emit("Slice", [src, i64([0], "st"), i64([half], "en"),
                                i64([3], "ax"), i64([1], "sp")],
                      [dst + "_h1"])
            self.emit("Neg", [dst + "_h2"], [dst + "_n"])
            self.emit("Concat", [dst + "_n", dst + "_h1"],
                      [dst + "_rot"], [P.attr_int("axis", 3)])
            self.emit("Mul", [src, cosc], [dst + "_tc"])
            self.emit("Mul", [dst + "_rot", sinc], [dst + "_rs"])
            self.emit("Add", [dst + "_tc", dst + "_rs"], [dst])

        for i, nm in enumerate("qkv"):
            self.emit("Transpose", [ins[i]], [f"{t}_{nm}t"],
                      [P.attr_ints("perm", perm)])
        rope(f"{t}_qt", f"{t}_qr")
        rope(f"{t}_kt", f"{t}_kr")
        self.shapes[f"{t}_qr"] = (qs[0], qs[2], qs[1], qs[3])
        self.emit("Transpose", [f"{t}_kr"], [f"{t}_kT"],
                  [P.attr_ints("perm", [0, 1, 3, 2])])
        self._sdpa_chain(t, f"{t}_qr", f"{t}_kT", f"{t}_vt", outs, dt,
                         S, S, bool(cv.get("is_causal")))

    def _op_unsqueeze(self, ins, outs, cv, stmt):
        ax = cv.get("axis")
        axes = sorted(int(a) for a in
                      (ax if isinstance(ax, (list, tuple)) else [ax]))
        # ONNX Unsqueeze-13 takes negative axes relative to the OUTPUT
        # rank (same as a single expand_dims); the eager op applies
        # sorted axes sequentially, which only matches the all-at-once
        # ONNX semantics when multi-axis lists are non-negative
        if len(axes) > 1 and any(a < 0 for a in axes):
            raise NotImplementedError(
                "ONNX export: multiple negative unsqueeze axes")
        a_in = self.const(np.asarray(axes, np.int64), "axes")
        self.emit("Unsqueeze", [ins[0], a_in], outs)

    def _op_squeeze(self, ins, outs, cv, stmt):
        ax = cv.get("axis")
        shape = self.shapes.get(ins[0])
        if ax is None:
            if shape is None:
                raise NotImplementedError(
                    "ONNX export: squeeze(all) needs a static shape")
            axes = [i for i, s in enumerate(shape) if s == 1]
        else:
            axes = [int(a) for a in
                    (ax if isinstance(ax, (list, tuple)) else [ax])]
            if shape is not None:
                # eager semantics: silently keep non-1 dims
                axes = [a % len(shape) for a in axes
                        if shape[a % len(shape)] == 1]
        if not axes:
            # real runtimes treat an EMPTY axes tensor as
            # squeeze-all-unit-dims — emit the intended no-op instead
            self.emit("Identity", ins, outs)
            return
        a_in = self.const(np.asarray(sorted(axes), np.int64), "axes")
        self.emit("Squeeze", [ins[0], a_in], outs)

    def _op_embedding(self, ins, outs, cv, stmt):
        """op inputs are (indices, weight); ONNX Gather wants
        (data, indices)."""
        if cv.get("padding_idx") is not None:
            raise NotImplementedError(
                "ONNX export: embedding with padding_idx")
        self.emit("Gather", [ins[1], ins[0]], outs,
                  [P.attr_int("axis", 0)])

    def _op_layer_norm(self, ins, outs, cv, stmt):
        """ONNX LayerNormalization (opset 17): normalizes axes
        [rank - nd, rank); scale/bias carry the normalized shape."""
        x = ins[0]
        shape = self.shapes.get(x)
        if shape is None:
            raise NotImplementedError(
                "ONNX export: layer_norm needs a static input shape")
        nd = int(cv.get("nd", 1))
        axis = len(shape) - nd
        rest = list(ins[1:])
        w = rest.pop(0) if cv.get("weight") is not None else None
        b = rest.pop(0) if cv.get("bias") is not None else None
        if w is None:
            dt = self.dtypes.get(x, np.dtype(np.float32))
            w = self.const(
                np.ones(tuple(int(s) for s in shape[axis:]), dt),
                "ln_scale")
        node_ins = [x, w] + ([b] if b is not None else [])
        self.emit("LayerNormalization", node_ins, outs,
                  [P.attr_int("axis", axis),
                   P.attr_float("epsilon",
                                float(cv.get("epsilon", 1e-5)))])
        self.min_opset = max(self.min_opset, 17)

    def _op_gelu(self, ins, outs, cv, stmt):
        """Exact gelu decomposed as 0.5*x*(1+Erf(x/sqrt(2))) — Erf is
        opset 9, so transformer graphs stay broadly loadable."""
        if cv.get("approximate"):
            raise NotImplementedError(
                "ONNX export: tanh-approximate gelu — use exact gelu "
                "or export via jit.save (StableHLO)")
        x = ins[0]
        dt = self.dtypes.get(x, np.dtype(np.float32))
        inv = self.const(np.asarray(1.0 / np.sqrt(2.0), dt), "isqrt2")
        half = self.const(np.asarray(0.5, dt), "half")
        one = self.const(np.asarray(1.0, dt), "one")
        t = outs[0]
        self.emit("Mul", [x, inv], [t + "_s"])
        self.emit("Erf", [t + "_s"], [t + "_e"])
        self.emit("Add", [t + "_e", one], [t + "_a"])
        self.emit("Mul", [x, t + "_a"], [t + "_m"])
        self.emit("Mul", [t + "_m", half], outs)

    def _op_expand(self, ins, outs, cv, stmt):
        """broadcast_to -> ONNX Expand with the statically-known output
        shape (eval_shape already resolved -1 dims)."""
        out_shape = self.shapes.get(outs[0])
        if out_shape is None:
            raise NotImplementedError(
                "ONNX export: expand needs a static output shape")
        shp = self.const(
            np.asarray([int(s) for s in out_shape], np.int64), "shape")
        self.emit("Expand", [ins[0], shp], outs)

    _op_expand_as = _op_expand

    def _op_rms_norm(self, ins, outs, cv, stmt):
        """Fused RMSNorm decomposed to ReduceMean/Sqrt/Div (+ Mul by
        the weight when present) — all opset-13 ops."""
        x = ins[0]
        dt = self.dtypes.get(x, np.dtype(np.float32))
        if dt == np.dtype(np.float16) or str(dt) == "bfloat16":
            raise NotImplementedError(
                "ONNX export: rms_norm in reduced precision computes "
                "stats in f32 — export a float32 model")
        eps = self.const(
            np.asarray(float(cv.get("epsilon", 1e-6)), dt), "eps")
        t = outs[0]
        self.emit("Mul", [x, x], [t + "_sq"])
        self.emit("ReduceMean", [t + "_sq"], [t + "_ms"],
                  [P.attr_ints("axes", [-1]), P.attr_int("keepdims", 1)])
        self.emit("Add", [t + "_ms", eps], [t + "_mse"])
        self.emit("Sqrt", [t + "_mse"], [t + "_rms"])
        has_w = len(ins) > 1
        div_out = t + "_n" if has_w else outs[0]
        self.emit("Div", [x, t + "_rms"], [div_out])
        if has_w:
            self.emit("Mul", [div_out, ins[1]], outs)

    def _op_silu(self, ins, outs, cv, stmt):
        t = outs[0]
        self.emit("Sigmoid", ins, [t + "_sg"])
        self.emit("Mul", [ins[0], t + "_sg"], outs)

    def _op_swiglu(self, ins, outs, cv, stmt):
        """silu(a) * b — the fused Llama MLP gate.  The packed
        single-input form splits x in half on the last axis first."""
        t = outs[0]
        if len(ins) == 1:
            self.emit("Split", [ins[0]], [t + "_a", t + "_b"],
                      [P.attr_int("axis", -1)])
            a, b = t + "_a", t + "_b"
        else:
            a, b = ins[0], ins[1]
        self.emit("Sigmoid", [a], [t + "_sg"])
        self.emit("Mul", [a, t + "_sg"], [t + "_si"])
        self.emit("Mul", [t + "_si", b], outs)

    def _op_leaky_relu(self, ins, outs, cv, stmt):
        self.emit("LeakyRelu", ins, outs,
                  [P.attr_float("alpha",
                                float(cv.get("negative_slope", 0.01)))])

    def _op_interpolate(self, ins, outs, cv, stmt):
        """nearest-mode upsampling with an integer scale (the detector/
        segmentation skip-connection case) -> ONNX Resize with a scales
        input; other modes/fractional scales fall back to jit.save."""
        if cv.get("mode", "nearest") != "nearest":
            raise NotImplementedError(
                "ONNX export: interpolate mode="
                f"{cv.get('mode')!r} — only 'nearest' is supported; "
                "export via jit.save (StableHLO) instead")
        if cv.get("channel_last"):
            raise NotImplementedError("ONNX export: NHWC interpolate")
        in_shape = self.shapes.get(ins[0])
        out_shape = self.shapes.get(outs[0])
        if in_shape is None or out_shape is None:
            raise NotImplementedError(
                "ONNX export: interpolate needs static shapes")
        scales = [float(o) / float(i)
                  for o, i in zip(out_shape, in_shape)]
        if any(s != int(s) for s in scales[2:]):
            raise NotImplementedError(
                "ONNX export: non-integer interpolate scale "
                f"{scales[2:]}")
        sc = self.const(np.asarray(scales, np.float32), "scales")
        # Resize(X, roi, scales) — roi unused for nearest (empty name)
        self.emit("Resize", [ins[0], "", sc], outs,
                  [P.attr_str("mode", "nearest")])

    def _op_adaptive_avg_pool2d(self, ins, outs, cv, stmt):
        """output_size=1 is exactly ONNX GlobalAveragePool; any other
        static output size lowers to AveragePool when the input splits
        evenly (the torchvision/zoo cases)."""
        if cv.get("channel_last"):
            raise NotImplementedError(
                "ONNX export: NHWC adaptive_avg_pool2d")
        osz = _pair(cv.get("out_sz") or 1)
        in_shape = self.shapes.get(ins[0])
        if tuple(osz) == (1, 1):
            self.emit("GlobalAveragePool", ins, outs)
            return
        if in_shape is None or len(in_shape) != 4:
            raise NotImplementedError(
                "ONNX export: adaptive_avg_pool2d needs a static NCHW "
                "input shape")
        H, W = int(in_shape[2]), int(in_shape[3])
        # None output axes keep the input size (identity on that axis)
        osz = [H if osz[0] is None else int(osz[0]),
               W if osz[1] is None else int(osz[1])]
        if H % osz[0] or W % osz[1]:
            raise NotImplementedError(
                "ONNX export: adaptive_avg_pool2d with non-divisible "
                f"output size {osz} for input {H}x{W}")
        k = [H // osz[0], W // osz[1]]
        self.emit("AveragePool", ins, outs,
                  [P.attr_ints("kernel_shape", k),
                   P.attr_ints("strides", k)])

    def _op_batch_norm(self, ins, outs, cv, stmt):
        """Eval-mode batch_norm -> ONNX BatchNormalization.  Op input
        order is (x, mean, var[, weight][, bias]) per F.batch_norm;
        ONNX wants (X, scale, B, input_mean, input_var).  Training mode
        recomputes batch statistics and is not exportable — call
        model.eval() first (same contract as the reference's
        paddle2onnx path)."""
        if not cv.get("use_stats", False):
            raise NotImplementedError(
                "ONNX export: batch_norm in training mode — call "
                "model.eval() before export")
        if cv.get("channel_axis", 1) != 1:
            raise NotImplementedError("ONNX export: NHWC batch_norm")
        x, mean, var = ins[0], ins[1], ins[2]
        rest = list(ins[3:])
        scale = rest.pop(0) if cv.get("weight") is not None else None
        bias = rest.pop(0) if cv.get("bias") is not None else None
        if scale is None or bias is None:
            shape = self.shapes.get(x)
            if shape is None or len(shape) < 2:
                raise NotImplementedError(
                    "ONNX export: affine-less batch_norm needs a "
                    "static input shape to synthesize scale/bias")
            ch = int(shape[1])
            # ONNX requires scale/B to match X's dtype
            dt = self.dtypes.get(x, np.dtype(np.float32))
            if scale is None:
                scale = self.const(np.ones(ch, dt), "bn_scale")
            if bias is None:
                bias = self.const(np.zeros(ch, dt), "bn_bias")
        self.emit("BatchNormalization", [x, scale, bias, mean, var],
                  outs,
                  [P.attr_float("epsilon",
                                float(cv.get("epsilon", 1e-5)))])

    def _op_softmax(self, ins, outs, cv, stmt):
        self.emit("Softmax", ins, outs,
                  [P.attr_int("axis", int(cv.get("axis", -1)))])

    def _op_concat(self, ins, outs, cv, stmt):
        # the recorder (ops.manipulation.concat) closes over ``ax``
        self.emit("Concat", ins, outs,
                  [P.attr_int("axis", int(cv.get("ax", 0)))])


_SIMPLE = {
    "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh", "exp": "Exp",
    "sqrt": "Sqrt", "add": "Add", "subtract": "Sub", "multiply": "Mul",
    "divide": "Div", "neg": "Neg", "elementwise_add": "Add",
    "erf": "Erf", "log": "Log", "abs": "Abs", "floor": "Floor",
    "ceil": "Ceil", "sin": "Sin", "cos": "Cos",
}
_SPECIAL = ["linear", "matmul", "conv2d", "max_pool2d", "avg_pool2d",
            "flatten", "reshape", "transpose", "softmax", "concat",
            "batch_norm", "adaptive_avg_pool2d", "leaky_relu",
            "interpolate", "unsqueeze", "squeeze", "embedding",
            "layer_norm", "gelu", "flash_attention_pallas", "getitem",
            "rms_norm", "silu", "swiglu", "flash_attention_rope",
            "expand", "expand_as"]


def _elem_type(dtype) -> int:
    return P._NP2ONNX.get(np.dtype(dtype), P.FLOAT)


def program_to_onnx(program, out_tensors, opset: int = 13,
                    declared_shapes: Dict[str, list] = None) -> bytes:
    """Convert a captured static Program to ONNX ModelProto bytes.

    ``declared_shapes``: optional feed-name -> shape with None for
    dynamic dims (emitted as dim_param); the capture itself always runs
    on concrete shapes."""
    import jax

    if opset > 17:
        raise NotImplementedError(
            "ONNX export targets opsets 13-17: ReduceMean (and other "
            "emitted nodes) use the axes-ATTRIBUTE form that opset 18 "
            "moved to an input")
    rec = program.recorder
    conv = _Converter()
    declared_shapes = declared_shapes or {}

    sym_name: Dict[int, str] = {}
    sym_sd: Dict[int, "jax.ShapeDtypeStruct"] = {}
    inputs = []
    for feed_name, t in program.feeds:
        # input_sym_of, NOT _sym_of[id]: an aliasing op (identity slice,
        # same-shape reshape) can return the placeholder's buffer and
        # remap its id to the op's OUTPUT sym
        sym = rec.input_sym_of(t)
        sym_name[sym] = feed_name
        sym_sd[sym] = jax.ShapeDtypeStruct(tuple(t.shape),
                                           np.dtype(str(t.dtype)))
        conv.shapes[feed_name] = tuple(t.shape)
        conv.dtypes[feed_name] = np.dtype(str(t.dtype))
        decl = declared_shapes.get(feed_name, list(t.shape))
        inputs.append(P.value_info(feed_name,
                                   _elem_type(str(t.dtype)), decl))

    # captured weights -> initializers
    for cap_t, sym in rec._captures.values():
        name = f"w_{sym}"
        sym_name[sym] = name
        arr = np.asarray(cap_t._value)
        sym_sd[sym] = jax.ShapeDtypeStruct(arr.shape, arr.dtype)
        conv.shapes[name] = tuple(arr.shape)
        conv.dtypes[name] = arr.dtype
        conv.initializers.append(P.tensor_proto(name, arr))

    for si, stmt in enumerate(rec.statements):
        # scalar constants take the dtype of the first tensor operand so
        # binary ops stay type-consistent in the exported graph
        ref_dtype = np.float32
        for kind, val in stmt.arg_spec:
            if kind == "s":
                ref_dtype = sym_sd[val].dtype
                break
        ins = []
        eval_args = []
        for kind, val in stmt.arg_spec:
            if kind == "s":
                ins.append(sym_name[val])
                eval_args.append(sym_sd[val])
            elif kind == "c":
                eval_args.append(val)
                if isinstance(val, (int, float)):
                    ins.append(conv.const(
                        np.asarray(val, ref_dtype), "scalar"))
                elif isinstance(val, (np.ndarray,)) or hasattr(
                        val, "shape"):
                    ins.append(conv.const(np.asarray(val), "baked"))
                elif val is None:
                    ins.append("")
                else:
                    raise NotImplementedError(
                        f"ONNX export: constant arg {type(val)} in "
                        f"op '{stmt.name}'")
            else:
                raise NotImplementedError(
                    f"ONNX export: op '{stmt.name}' draws RNG (train-"
                    "mode graph?) — export in eval mode")
        out_sd = jax.eval_shape(
            lambda *a: stmt.fn(*a, **stmt.kwargs), *eval_args)
        flat_sd = out_sd if isinstance(out_sd, tuple) else (out_sd,)
        outs = []
        for osym, sd in zip(stmt.out_syms, flat_sd):
            n = f"t_{osym}"
            sym_name[osym] = n
            sym_sd[osym] = sd
            conv.shapes[n] = tuple(sd.shape)
            conv.dtypes[n] = np.dtype(sd.dtype)
            outs.append(n)
        conv.convert(stmt, ins, outs)

    outputs = []
    for i, t in enumerate(out_tensors):
        sym = rec._sym_of.get(id(t._value))
        if sym is None or sym not in sym_name:
            raise ValueError("output tensor was not produced by the "
                             "captured program")
        outputs.append(P.value_info(sym_name[sym],
                                    _elem_type(str(t.dtype)),
                                    list(t.shape)))

    g = P.graph(conv.nodes, program.name, inputs, outputs,
                conv.initializers)
    if conv.min_opset > opset:
        # never silently emit a model at a different opset than the one
        # the caller pinned: a deploy pipeline that validates against
        # opset N must find out at export time, not at load time
        import warnings
        warnings.warn(
            f"ONNX export: requested opset {opset} but the converted "
            f"graph uses ops that require opset {conv.min_opset} "
            f"(e.g. LayerNormalization needs 17); emitting opset "
            f"{conv.min_opset}", UserWarning, stacklevel=2)
    return P.model(g, opset=max(opset, conv.min_opset))
