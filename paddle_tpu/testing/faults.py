"""Fault-injection harness.

Robustness code paths (atomic checkpoints, I/O retry, collective
watchdogs) are only trustworthy if tests can MAKE the failure happen at
the exact instrumented instant.  Production code marks those instants
with :func:`fault_point("site.name")`; a fault spec — from the
``PADDLE_TPU_FAULT_SPEC`` environment variable or an in-process
:func:`configure` call — decides what each hit does.

Spec syntax (';'-separated rules)::

    <mode>:<site-glob>[:key=value]*

    modes:
      ioerror         raise FaultError (an OSError subclass)
      kill            SIGKILL the whole process (kill -9 semantics:
                      no cleanup, no atexit, no finally blocks)
      delay           sleep ``ms`` milliseconds, then continue
      hang            sleep ``ms`` (default 3600000), for watchdog tests
      drop            raise FaultDrop — the instrumented I/O "happened"
                      but its bytes vanished (a lost datagram/frame);
                      the RPC layer swallows it and lets the reply
                      deadline discover the loss
      econnreset      raise ConnectionResetError (peer RST mid-stream)

    keys:
      after=N         arm on the N-th hit of a matching site (1-based,
                      counted per rule; default 1)
      times=M         fire at most M times once armed (default: kill
                      fires once, everything else fires forever)
      ms=T            delay/hang duration in milliseconds (delay
                      default 100)

Examples::

    PADDLE_TPU_FAULT_SPEC="kill:ckpt.write:after=2"
    PADDLE_TPU_FAULT_SPEC="ioerror:io.save:times=2"      # retries succeed
    PADDLE_TPU_FAULT_SPEC="delay:ckpt.gather:ms=300"     # watchdog food

Sites are matched with fnmatch globs, so ``ckpt.*`` covers every
checkpoint-write instant.  The harness is inert (one dict lookup) when
no spec is installed.

Network sites (round 23 — the fleet RPC layer, both sides of the
wire; the injector is process-global, so a client-process spec and a
server-subprocess spec never collide)::

    rpc.send     just before a frame is written (client request or
                 server response); ``drop`` makes that frame vanish
    rpc.recv     a complete frame just arrived (client reply or server
                 request); ``drop`` discards it unprocessed
    rpc.accept   a connection was just accepted; ``econnreset`` closes
                 it before any frame is read

    PADDLE_TPU_FAULT_SPEC="drop:rpc.send:after=2:times=1"   # one lost rpc
    PADDLE_TPU_FAULT_SPEC="econnreset:rpc.recv"             # flaky peer
    PADDLE_TPU_FAULT_SPEC="hang:rpc.recv:ms=2000"           # stuck server
"""
from __future__ import annotations

import fnmatch
import os
import signal
import threading
import time
from typing import Dict, List, Optional

__all__ = ["FaultRule", "FaultInjector", "FaultError", "FaultDrop",
           "fault_point", "configure", "active_spec", "reset", "ENV_VAR"]

ENV_VAR = "PADDLE_TPU_FAULT_SPEC"

_MODES = ("ioerror", "kill", "delay", "hang", "drop", "econnreset")


class FaultError(OSError):
    """The injected I/O failure (an OSError so real retry/backoff code
    handles it like a transient disk error)."""


class FaultDrop(Exception):
    """The instrumented operation "happened" but its bytes vanished —
    a lost frame/datagram.  Deliberately NOT an OSError: the RPC layer
    catches it exactly at the fault point and continues silently, so
    the loss is only discovered by the reply deadline (the realistic
    packet-loss failure shape, not a synchronous error)."""


class FaultRule:
    """One parsed ``mode:site[:k=v]*`` clause."""

    def __init__(self, mode: str, site: str, after: int = 1,
                 times: Optional[int] = None, ms: Optional[float] = None):
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r}; one of {_MODES}")
        self.mode = mode
        self.site = site
        self.after = max(1, int(after))
        if times is None:
            times = 1 if mode == "kill" else -1    # -1 = unbounded
        self.times = int(times)
        if ms is None:
            ms = 3.6e6 if mode == "hang" else 100.0
        self.ms = float(ms)
        self.hits = 0          # matching fault_point() calls seen
        self.fired = 0

    @classmethod
    def parse(cls, clause: str) -> "FaultRule":
        parts = [p for p in clause.strip().split(":") if p]
        if len(parts) < 2:
            raise ValueError(
                f"fault clause {clause!r} must be mode:site[:k=v]*")
        mode, site, kv = parts[0], parts[1], parts[2:]
        kwargs = {}
        for item in kv:
            k, _, v = item.partition("=")
            if k not in ("after", "times", "ms"):
                raise ValueError(f"unknown fault key {k!r} in {clause!r}")
            kwargs[k] = float(v) if k == "ms" else int(v)
        return cls(mode, site, **kwargs)

    def matches(self, site: str) -> bool:
        return fnmatch.fnmatchcase(site, self.site)

    def should_fire(self) -> bool:
        """Count this hit; True if the rule is armed and not exhausted."""
        self.hits += 1
        if self.hits < self.after:
            return False
        if self.times >= 0 and self.fired >= self.times:
            return False
        self.fired += 1
        return True

    def __repr__(self):
        return (f"FaultRule({self.mode}:{self.site} after={self.after} "
                f"times={self.times} ms={self.ms})")


class FaultInjector:
    """Holds the active rules; thread-safe (checkpoint writers run in
    background threads)."""

    def __init__(self, spec: str = ""):
        self.spec = spec or ""
        self.rules: List[FaultRule] = [
            FaultRule.parse(c) for c in self.spec.split(";") if c.strip()]
        self._lock = threading.Lock()
        self.log: List[str] = []        # fired "mode:site" records

    def hit(self, site: str):
        for rule in self.rules:
            if not rule.matches(site):
                continue
            with self._lock:
                fire = rule.should_fire()
            if not fire:
                continue
            self.log.append(f"{rule.mode}:{site}")
            if rule.mode == "ioerror":
                raise FaultError(
                    f"injected I/O error at fault point {site!r}")
            if rule.mode == "drop":
                raise FaultDrop(
                    f"injected byte loss at fault point {site!r}")
            if rule.mode == "econnreset":
                raise ConnectionResetError(
                    f"injected connection reset at fault point {site!r}")
            if rule.mode == "kill":
                # kill -9 the real process: the point is proving that
                # NOTHING after this line (flush, rename, finally)
                # happens, exactly like a preemption
                os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(60)     # never reached; belt and braces
            if rule.mode in ("delay", "hang"):
                time.sleep(rule.ms / 1000.0)


# -- process-global injector ------------------------------------------------
# Lazily (re)built: the env var is read once per configure()/first use, so
# subprocess tests just set the env before exec and never import us first.
_injector: List[Optional[FaultInjector]] = [None]
_env_seen: List[Optional[str]] = [None]


def configure(spec: Optional[str]) -> FaultInjector:
    """Install a spec in-process (overrides the env var); None/'' resets
    to inert."""
    _injector[0] = FaultInjector(spec or "")
    _env_seen[0] = None if spec else ""
    return _injector[0]


def reset():
    _injector[0] = None
    _env_seen[0] = None


def active_spec() -> Optional[FaultInjector]:
    env = os.environ.get(ENV_VAR, "")
    if _injector[0] is None or (_env_seen[0] is not None
                                and env != _env_seen[0]):
        _injector[0] = FaultInjector(env)
        _env_seen[0] = env
    return _injector[0]


def fault_point(site: str):
    """Mark an injectable instant.  Inert unless a matching rule is
    installed."""
    inj = active_spec()
    if inj is not None and inj.rules:
        inj.hit(site)
