"""Testing utilities: fault injection for robustness tests, and the
shared multichip CPU-dryrun setup.

Parity: the reference exercises its fault-tolerance paths with chaos
tests under test/collective/fleet (kill-one-rank elastic relaunch) and
the checkpoint layer's corruption unit tests; here the injection points
are first-class so any test can script a failure scenario through
``PADDLE_TPU_FAULT_SPEC``.
"""
from .dryrun import force_cpu_devices
from .faults import (FaultRule, FaultInjector, FaultError, fault_point,
                     configure, active_spec, reset)

__all__ = ["FaultRule", "FaultInjector", "FaultError", "fault_point",
           "configure", "active_spec", "reset", "force_cpu_devices"]
