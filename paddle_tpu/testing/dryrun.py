"""Multichip CPU dryrun setup — ONE helper instead of N hand-rolled
``--xla_force_host_platform_device_count`` blocks.

Every multichip bench/test used to copy the same dance: set
``JAX_PLATFORMS=cpu`` + the XLA flag before any jax import, with a
``jax_num_cpu_devices`` fallback for newer jax.  The copies drifted
(some handled an already-initialized backend, some didn't), so the
logic now lives here and is consumed by ``tools/bench_serving.py
--tp``, ``bench.py --sharded-update`` (via ``tools/
bench_sharded_update.py``), ``tools/bench_checkpoint.py``,
``__graft_entry__``, and the multichip tests.

Importing this module is safe at any point: ``paddle_tpu`` never
initializes a jax backend at import time, and the helper tears down and
re-initializes live backends when the caller got here late.
"""
from __future__ import annotations

import os

__all__ = ["force_cpu_devices", "cpu_mesh_2d", "cpu_mesh_cp"]


def force_cpu_devices(n_devices: int = 8) -> None:
    """Force JAX onto ``n_devices`` virtual CPU devices, before OR
    after a backend has been initialized.  Must not touch any real TPU
    client.

    jax-version notes (0.4.x vs >= 0.5): 0.4.x only honors the
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` env path and
    it must be set before the CPU client initializes; newer jax has the
    ``jax_num_cpu_devices`` config instead.  Both are handled here.
    """
    import re
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_PLATFORM_NAME", None)
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None or int(m.group(1)) < n_devices:
        # replace a pre-set smaller count (e.g. leftover single-device
        # debugging) instead of keeping it — on jax 0.4.x this flag is
        # the only path, so an under-sized value would fail the final
        # device-count assert; a larger pre-set count is left alone
        if m is not None:
            flags = flags.replace(m.group(0), "").strip()
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    import jax
    from jax._src import xla_bridge

    def _drop_live_backends():
        # jax_num_cpu_devices must be set before backends initialize, so
        # if the caller already touched jax.devices() (even on a TPU),
        # tear the clients down and let them re-initialize under the new
        # config/env on the next jax.devices() call.
        jax.clear_caches()
        try:
            jax.extend.backend.clear_backends()
        except Exception:
            xla_bridge._clear_backends()

    if getattr(xla_bridge, "_backends", None):
        # a live backend that already satisfies the request must be a
        # NO-OP (tests import this after conftest forced the mesh —
        # tearing it down would invalidate every live array)
        if jax.devices()[0].platform == "cpu" \
                and jax.device_count() >= n_devices:
            return
        _drop_live_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # jax 0.4.x has no jax_num_cpu_devices option; the
        # xla_force_host_platform_device_count XLA_FLAGS path (set
        # above, applied when the CPU client initializes) covers it
        pass
    except Exception:
        _drop_live_backends()
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except AttributeError:
            pass
    if not (jax.devices()[0].platform == "cpu"
            and jax.device_count() >= n_devices):
        _drop_live_backends()
    assert jax.devices()[0].platform == "cpu", "CPU forcing failed"
    assert jax.device_count() >= n_devices, (
        f"only {jax.device_count()} CPU devices, wanted {n_devices}")


def cpu_mesh_2d(fsdp: int, tp: int, replica: int = 1):
    """First-class 2D dryrun mesh (round 21): force enough virtual CPU
    devices for an ``fsdp x tp`` (optionally ``dp x fsdp x tp``) mesh
    and return the :func:`paddle_tpu.jit.spmd.mesh_2d` ProcessMesh over
    them.  The one-liner behind the 2D tests and ``tools/
    bench_spmd2d.py`` — replaces ad-hoc ``force_cpu_devices(N)`` +
    hand-built ``ProcessMesh`` pairs, and never shrinks an
    already-forced larger device count (safe under the conftest-forced
    8-device mesh)."""
    force_cpu_devices(max(replica * fsdp * tp, 1))
    from ..jit.spmd import mesh_2d
    return mesh_2d(fsdp, tp, replica=replica)


def cpu_mesh_cp(cp: int, tp: int = 1):
    """Context-parallel dryrun mesh (round 22): force enough virtual
    CPU devices for a ``cp`` (optionally ``cp x tp``) mesh and return
    the :func:`paddle_tpu.jit.spmd.cp_mesh` ProcessMesh over them —
    the one-liner behind the cp tests and ``tools/bench_serving.py
    --cp``."""
    force_cpu_devices(max(cp * tp, 1))
    from ..jit.spmd import cp_mesh
    return cp_mesh(cp, tp=tp)
