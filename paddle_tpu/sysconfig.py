"""paddle.sysconfig (parity: python/paddle/sysconfig.py)."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "libs")
