"""Functional higher-order autograd.

Parity: python/paddle/incubate/autograd/functional.py (reference) — here
delegated to JAX transforms over the functional core, which is strictly more
capable (arbitrary-order, forward+reverse composition).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _fnize(func):
    def f(*vals):
        ts = [Tensor._from_value(v) for v in vals]
        out = func(*ts)
        return out._value if isinstance(out, Tensor) else out
    return f


def _vals(xs):
    if isinstance(xs, (list, tuple)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def _tensorize(xs):
    xs_list = list(xs) if isinstance(xs, (list, tuple)) else [xs]
    out = []
    for x in xs_list:
        if not isinstance(x, Tensor):
            x = Tensor(jnp.asarray(x), stop_gradient=False)
        out.append(x)
    return out


def _rows_to_jacobian(rows, out_shape, in_tensor):
    """Stack one-hot vjp rows (Tensors) into out_shape + in_shape, keeping
    the tape history (create_graph path)."""
    import paddle_tpu as P
    stacked = P.stack(rows)
    return stacked.reshape(list(out_shape) + list(in_tensor._value.shape))


def _eager_jacobian_rows(out, xs_list, allow_unused):
    """One grad(create_graph=True) row per scalar element of ``out``."""
    from .tape import grad as _grad
    per_input = [[] for _ in xs_list]
    out_v = out._value
    n = int(out_v.size)
    for i in range(n):
        seed = jnp.zeros((n,), out_v.dtype).at[i].set(1).reshape(out_v.shape)
        gs = _grad([out], xs_list, grad_outputs=[Tensor._from_value(seed)],
                   create_graph=True, retain_graph=True,
                   allow_unused=allow_unused)
        for k, g in enumerate(gs):
            if g is None:
                g = Tensor._from_value(jnp.zeros_like(xs_list[k]._value))
            per_input[k].append(g)
    return per_input


def jacobian(func, xs, create_graph=False, allow_unused=False):
    if create_graph:
        # Eager double-grad path: every row is a paddle.grad(create_graph)
        # call, so the returned jacobian carries tape history and can be
        # differentiated again (parity: paddle.autograd.jacobian used inside
        # gradient-penalty losses).
        xs_list = _tensorize(xs)
        out = func(*xs_list)
        per_input = _eager_jacobian_rows(out, xs_list, allow_unused)
        jacs = [_rows_to_jacobian(rows, out._value.shape, x)
                for rows, x in zip(per_input, xs_list)]
        return jacs[0] if len(jacs) == 1 else tuple(jacs)
    vals = _vals(xs)
    jac = jax.jacrev(_fnize(func), argnums=tuple(range(len(vals))))(*vals)
    if len(vals) == 1:
        return Tensor._from_value(jac[0])
    return tuple(Tensor._from_value(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    if create_graph:
        from .tape import grad as _grad
        xs_list = _tensorize(xs)
        out = func(*xs_list)
        g1 = _grad([out], xs_list, create_graph=True, retain_graph=True,
                   allow_unused=allow_unused)
        if isinstance(g1, Tensor):
            g1 = [g1]
        blocks = []
        for k, gk in enumerate(g1):
            if gk is None:   # unused input under allow_unused: zero blocks
                blocks.append(tuple(
                    Tensor._from_value(jnp.zeros(
                        tuple(xs_list[k]._value.shape)
                        + tuple(x._value.shape),
                        xs_list[k]._value.dtype))
                    for x in xs_list))
                continue
            # inner rows always zero-fill: a structurally-zero cross block
            # (separable f) is a valid hessian entry, not a user error
            per_input = _eager_jacobian_rows(gk, xs_list, True)
            blocks.append(tuple(
                _rows_to_jacobian(rows, gk._value.shape, x)
                for rows, x in zip(per_input, xs_list)))
        if len(xs_list) == 1:
            return blocks[0][0]
        return tuple(blocks)
    vals = _vals(xs)
    hes = jax.hessian(_fnize(func), argnums=tuple(range(len(vals))))(*vals)
    if len(vals) == 1:
        return Tensor._from_value(hes[0][0])
    return hes


def vjp(func, xs, v=None):
    vals = _vals(xs)
    out, vjp_fn = jax.vjp(_fnize(func), *vals)
    if v is None:
        v = jnp.ones_like(out)
    else:
        v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
    grads = vjp_fn(v)
    grads = tuple(Tensor._from_value(g) for g in grads)
    return Tensor._from_value(out), grads if len(grads) > 1 else grads[0]


def jvp(func, xs, v=None):
    vals = _vals(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vs = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(t._value if isinstance(t, Tensor) else jnp.asarray(t)
                         for t in vs)
    out, tangent_out = jax.jvp(_fnize(func), tuple(vals), tangents)
    return Tensor._from_value(out), Tensor._from_value(tangent_out)
