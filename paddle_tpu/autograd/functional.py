"""Functional higher-order autograd.

Parity: python/paddle/incubate/autograd/functional.py (reference) — here
delegated to JAX transforms over the functional core, which is strictly more
capable (arbitrary-order, forward+reverse composition).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _fnize(func):
    def f(*vals):
        ts = [Tensor._from_value(v) for v in vals]
        out = func(*ts)
        return out._value if isinstance(out, Tensor) else out
    return f


def _vals(xs):
    if isinstance(xs, (list, tuple)):
        return [x._value if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs]
    return [xs._value if isinstance(xs, Tensor) else jnp.asarray(xs)]


def jacobian(func, xs, create_graph=False, allow_unused=False):
    vals = _vals(xs)
    jac = jax.jacrev(_fnize(func), argnums=tuple(range(len(vals))))(*vals)
    if len(vals) == 1:
        return Tensor._from_value(jac[0])
    return tuple(Tensor._from_value(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    vals = _vals(xs)
    hes = jax.hessian(_fnize(func), argnums=tuple(range(len(vals))))(*vals)
    if len(vals) == 1:
        return Tensor._from_value(hes[0][0])
    return hes


def vjp(func, xs, v=None):
    vals = _vals(xs)
    out, vjp_fn = jax.vjp(_fnize(func), *vals)
    if v is None:
        v = jnp.ones_like(out)
    else:
        v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
    grads = vjp_fn(v)
    grads = tuple(Tensor._from_value(g) for g in grads)
    return Tensor._from_value(out), grads if len(grads) > 1 else grads[0]


def jvp(func, xs, v=None):
    vals = _vals(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        vs = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(t._value if isinstance(t, Tensor) else jnp.asarray(t)
                         for t in vs)
    out, tangent_out = jax.jvp(_fnize(func), tuple(vals), tangents)
    return Tensor._from_value(out), Tensor._from_value(tangent_out)
