"""Autograd public API.

Parity: python/paddle/autograd/ (reference) — backward, grad, no_grad,
PyLayer, saved-tensor hooks.

Note: ``py_layer``/``functional`` are loaded lazily (module __getattr__) so
that core.tensor can import ``tape`` without a cycle.
"""
from .tape import (GradNode, run_backward, grad, no_grad, enable_grad,
                   is_grad_enabled, set_grad_enabled)

_LAZY = {
    "PyLayer": ("py_layer", "PyLayer"),
    "PyLayerContext": ("py_layer", "PyLayerContext"),
    "LegacyPyLayer": ("py_layer", "LegacyPyLayer"),
    "jacobian": ("functional", "jacobian"),
    "hessian": ("functional", "hessian"),
    "vjp": ("functional", "vjp"),
    "jvp": ("functional", "jvp"),
    "saved_tensors_hooks": ("saved_hooks", "saved_tensors_hooks"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        m = importlib.import_module(f".{mod}", __name__)
        val = getattr(m, attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity
    (python/paddle/autograd/backward_mode.py:23)."""
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors,
                                                   (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "vjp", "jvp", "GradNode"]
