"""Parity import path: paddle.autograd.ir_backward (reference PIR
backward builder, __all__ = [grad, calc_gradient, calc_gradient_helper]).

TPU-native: the "IR" is the captured tape; all three entry points reduce
to the tape engine (paddle_tpu/autograd/tape.py) — calc_gradient is the
static-program form the reference routes through the same machinery."""
from .tape import grad

__all__ = ["grad", "calc_gradient", "calc_gradient_helper"]


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference signature (ir_backward.calc_gradient): list-in/list-out
    gradients of targets w.r.t. inputs."""
    outs = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    gouts = target_gradients
    res = grad(list(outs), list(ins), grad_outputs=gouts,
               allow_unused=True)
    return res if isinstance(res, list) else [res]


def calc_gradient_helper(targets, inputs, target_gradients=None,
                         no_grad_set=None):
    """Returns the accumulated-grad map keyed by input (the reference
    returns a value->grad dict for the IR builder)."""
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    grads = calc_gradient(targets, ins, target_gradients, no_grad_set)
    return dict(zip([id(i) for i in ins], grads))
