"""Eager autograd: tape of GradNodes over JAX VJPs.

Capability parity with the reference's eager autograd engine
(reference: paddle/fluid/eager/grad_node_info.h:197 GradNodeBase,
paddle/fluid/eager/backward.cc:105-428 queue-based RunBackward with an
in-degree map, GradTensorHolder accumulation).

TPU-native design: instead of 850 hand-written grad kernels, every op's
backward is obtained from JAX's VJP transform at forward time
(``jax.vjp``) — residuals are held by the vjp closure (the analog of the
reference's TensorWrapper saved-tensor mechanism,
paddle/fluid/eager/tensor_wrapper.h).  The engine itself mirrors the
reference: in-degree counting + ready queue + per-node cotangent holders.
"""
from __future__ import annotations

import contextlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

# --------------------------------------------------------------------------
# grad-enabled switch (parity: paddle.no_grad / paddle.enable_grad)
# --------------------------------------------------------------------------
_GRAD_ENABLED = [True]


def is_grad_enabled() -> bool:
    return _GRAD_ENABLED[0]


def set_grad_enabled(mode: bool):
    class _Guard(contextlib.AbstractContextManager):
        def __init__(self, mode):
            self._prev = _GRAD_ENABLED[0]
            _GRAD_ENABLED[0] = bool(mode)

        def __exit__(self, *exc):
            _GRAD_ENABLED[0] = self._prev
            return False

    return _Guard(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling tape recording
    (parity: python/paddle/base/dygraph/base.py no_grad_)."""

    def __enter__(self):
        self._prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = False
        return self

    def __exit__(self, *exc):
        _GRAD_ENABLED[0] = self._prev
        return False


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        self._prev = _GRAD_ENABLED[0]
        _GRAD_ENABLED[0] = True
        return self

    def __exit__(self, *exc):
        _GRAD_ENABLED[0] = self._prev
        return False


# --------------------------------------------------------------------------
# GradNode
# --------------------------------------------------------------------------
class GradNode:
    """One recorded op on the tape.

    Mirrors GradNodeBase (reference: paddle/fluid/eager/grad_node_info.h:197):
    slot-ranked edges to producer nodes, plus a holder that accumulates
    incoming cotangents per output slot.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_meta", "n_outputs",
                 "out_is_tuple", "_hooks", "raw_fn", "tensor_vjp",
                 "raw_all_inputs", "raw_diff_pos", "__weakref__")

    def __init__(self, name: str, vjp_fn: Callable, inputs: Sequence,
                 out_meta: List[Tuple[Tuple[int, ...], Any]],
                 out_is_tuple: bool = False, raw_fn: Optional[Callable] = None,
                 tensor_vjp: Optional[Callable] = None):
        self.name = name
        self.vjp_fn = vjp_fn          # maps output cotangents -> input cotangents
        self.inputs = list(inputs)    # input Tensors (edges)
        self.out_meta = out_meta      # [(shape, dtype)] per output slot
        self.n_outputs = len(out_meta)
        self.out_is_tuple = out_is_tuple  # forward returned a tuple (even len-1)
        self._hooks: List[Callable] = []
        # Differentiable forward closure over exactly ``inputs``' values —
        # enables create_graph backward (higher-order) by re-deriving the VJP
        # inside a fresh differentiable op.  The TPU-native analog of the
        # reference's generated higher-order GradNodes
        # (paddle/fluid/eager/auto_code_generator/generator/eager_gen.py).
        self.raw_fn = raw_fn
        # When raw_fn spans ALL tensor inputs (dispatch sets these), the
        # full input list + the positions of the differentiable subset:
        self.raw_all_inputs = None
        self.raw_diff_pos = None
        # Alternative: a Tensor-level backward (PyLayer) — called with Tensor
        # cotangents under grad-enabled mode so it records its own tape nodes.
        self.tensor_vjp = tensor_vjp

    def parents(self):
        for t in self.inputs:
            if t.stop_gradient:
                continue
            node = t._grad_node
            if node is not None:
                yield node

    def __repr__(self):
        return f"GradNode({self.name}, n_out={self.n_outputs})"


class AccumulationLeaf:
    """Marker for leaf accumulation (reference:
    paddle/fluid/eager/accumulation/accumulation_node.h)."""


def _zeros_like_meta(meta):
    shape, dtype = meta
    return jnp.zeros(shape, dtype)


def _add_grad(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a + b


# --------------------------------------------------------------------------
# Engine
# --------------------------------------------------------------------------
def _node_backward_create_graph(node: GradNode, cots: Tuple):
    """Run ``node``'s backward as a *differentiable op* so the cotangent
    computation itself records tape nodes (higher-order autograd).

    Mechanism: the node stores its raw forward closure (``raw_fn``); the
    backward ``vjp(raw_fn)(cots)`` is re-derived inside a fresh closure that
    is differentiable in BOTH the primal inputs and the cotangents, and that
    closure is dispatched through ``apply_op`` — exactly like any forward op
    (parity: reference higher-order GradNodes from eager_gen.py, exercised by
    test/legacy_test/test_imperative_double_grad.py)."""
    from ..core.dispatch import apply_op

    if node.raw_fn is not None:
        if node.raw_all_inputs is None:
            raise AssertionError(
                f"node {node.name} has raw_fn but no raw_all_inputs — "
                "dispatch always sets both; a raw_fn spanning only the "
                "diff inputs would re-bake stop_gradient inputs as "
                "closure constants")
        # raw_fn spans ALL tensor inputs (incl. stop_gradient ones):
        # every one enters the dispatched grad op as a real argument —
        # program capture then records them as symbolic inputs — while
        # the VJP differentiates only the diff positions.
        k = len(node.raw_all_inputs)
        dpos = node.raw_diff_pos

        def _bwd(*args, _fn=node.raw_fn, _k=k, _dpos=dpos,
                 _tup=node.out_is_tuple):
            primals, cs = args[:_k], args[_k:]

            def f_diff(*dvals):
                full = list(primals)
                for p, dv in zip(_dpos, dvals):
                    full[p] = dv
                return _fn(*full)

            _, vjp = jax.vjp(f_diff, *[primals[p] for p in _dpos])
            return vjp(tuple(cs) if _tup else cs[0])

        outs = apply_op(node.name + "_grad", _bwd,
                        tuple(node.raw_all_inputs) + tuple(cots))
        return outs if isinstance(outs, tuple) else (outs,)
    if node.tensor_vjp is not None:
        from ..core.tensor import Tensor
        return tuple(
            g if g is None or isinstance(g, Tensor) else Tensor._from_value(g)
            for g in node.tensor_vjp(cots))
    if node.vjp_fn is None:
        raise RuntimeError(
            f"Trying to backward through {node.name} a second time; "
            "set retain_graph=True if this is intended.")
    raise RuntimeError(
        f"create_graph=True through node {node.name} is not supported: "
        "it declares neither a differentiable forward closure (raw_fn) "
        "nor a Tensor-level backward (tensor_vjp).")


def run_backward(tensors: Sequence, grad_tensors: Optional[Sequence] = None,
                 retain_graph: bool = False,
                 capture: Optional[Dict[int, Any]] = None,
                 write_leaf_grad: bool = True,
                 create_graph: bool = False):
    """Run reverse accumulation from ``tensors``.

    Mirrors egr::Backward / RunBackward (reference:
    paddle/fluid/eager/backward.cc:428,105): seed the queue with the output
    nodes, count in-degrees over the reachable subgraph, pop ready nodes,
    call their (compiled) VJPs, route cotangents along edges, accumulate
    ``.grad`` at leaves.

    ``capture``: optional dict id(tensor) -> accumulated cotangent; when given,
    cotangents flowing into those tensors are also recorded there (the analog
    of the reference's GeneralGrad partial-graph path,
    paddle/fluid/eager/general_grad.h).  ``write_leaf_grad=False`` suppresses
    ``.grad`` mutation (used by :func:`grad`).
    """
    from ..core.tensor import Tensor  # cycle-free at call time

    tensors = list(tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    grad_tensors = list(grad_tensors)
    if len(grad_tensors) != len(tensors):
        raise ValueError("grad_tensors must match tensors")

    # cotangent holders: node -> [per-output-slot grad or None]
    holders: Dict[GradNode, List[Any]] = {}
    roots: List[GradNode] = []

    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if create_graph:
            # Tensor-mode seeds: cotangents stay Tensors so backward ops
            # chain into a new tape graph.
            if g is None:
                if t._value.size != 1:
                    raise RuntimeError(
                        "grad can be implicitly created only for scalar "
                        "outputs")
                seed = Tensor._from_value(jnp.ones_like(t._value))
            else:
                seed = g if isinstance(g, Tensor) \
                    else Tensor._from_value(jnp.asarray(g))
        else:
            seed = g._value if isinstance(g, Tensor) else g
            if seed is None:
                if t._value.size != 1:
                    raise RuntimeError(
                        "grad can be implicitly created only for scalar "
                        "outputs")
                seed = jnp.ones_like(t._value)
            else:
                seed = jnp.asarray(seed)
        if capture is not None and id(t) in capture:
            capture[id(t)] = _add_grad(capture[id(t)], seed)
        if node is None:
            # Leaf with no history: backward() on it only seeds its own grad.
            if write_leaf_grad and not t.stop_gradient:
                t._accumulate_grad(seed._value if create_graph else seed)
            continue
        h = holders.setdefault(node, [None] * node.n_outputs)
        h[t._out_index] = _add_grad(h[t._out_index], seed)
        roots.append(node)

    if not roots:
        return

    # Reachable subgraph + in-degree map (reference backward.cc getInDegreeMap).
    indeg: Dict[GradNode, int] = {}
    seen = set()
    stack = list(roots)
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        indeg.setdefault(n, 0)
        for p in n.parents():
            indeg[p] = indeg.get(p, 0) + 1
            stack.append(p)

    queue = deque(n for n in indeg if indeg[n] == 0)
    # Roots always ready (they already have their seed cotangents).
    processed = set()

    while queue:
        node = queue.popleft()
        if id(node) in processed:
            continue
        processed.add(id(node))

        slot_grads = holders.get(node)
        if slot_grads is None:
            slot_grads = [None] * node.n_outputs
        # Fill missing output cotangents with zeros of the right meta, and
        # coerce dtypes to the recorded output dtype (cross-dtype edges can
        # arise from user casts between ops).
        if create_graph:
            cots = tuple(
                (g.astype(m[1]) if g.dtype != m[1] else g) if g is not None
                else Tensor._from_value(_zeros_like_meta(m))
                for g, m in zip(slot_grads, node.out_meta)
            )
            if node._hooks:
                # Hooks operate on raw cotangents; a hook that REPLACES a
                # slot detaches that slot's higher-order history (documented
                # limitation — hooks are observers, not graph ops).
                raw = tuple(c._value for c in cots)
                for hook in node._hooks:
                    raw = hook(raw)
                cots = tuple(
                    c if r is c._value else Tensor._from_value(r)
                    for c, r in zip(cots, raw))
            in_grads = _node_backward_create_graph(node, cots)
        else:
            cots = tuple(
                (g.astype(m[1]) if g is not None and hasattr(g, "dtype")
                 and g.dtype != m[1] else g) if g is not None
                else _zeros_like_meta(m)
                for g, m in zip(slot_grads, node.out_meta)
            )
            for hook in node._hooks:
                cots = hook(cots)
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"Trying to backward through {node.name} a second time; "
                    "set retain_graph=True if this is intended.")
            in_grads = node.vjp_fn(cots if node.out_is_tuple else cots[0])
        if not isinstance(in_grads, tuple):
            in_grads = (in_grads,)

        for t, gval in zip(node.inputs, in_grads):
            if gval is not None and hasattr(gval, "dtype") \
                    and gval.dtype == jax.dtypes.float0:
                gval = None
            if t.stop_gradient:
                continue  # edge pruned (consistent with parents())
            pnode = t._grad_node
            if gval is not None:
                if capture is not None and id(t) in capture:
                    capture[id(t)] = _add_grad(capture[id(t)], gval)
                if pnode is None:
                    if write_leaf_grad:
                        t._accumulate_grad(
                            gval._value if create_graph
                            and isinstance(gval, Tensor) else gval)
                else:
                    h = holders.setdefault(pnode, [None] * pnode.n_outputs)
                    h[t._out_index] = _add_grad(h[t._out_index], gval)
            if pnode is not None:
                indeg[pnode] -= 1
                if indeg[pnode] <= 0:
                    queue.append(pnode)

        holders.pop(node, None)
        if not retain_graph and not create_graph:
            node.vjp_fn = None  # free residuals eagerly
            node.raw_fn = None
            node.raw_all_inputs = None
            node.tensor_vjp = None

    # Any nodes left with pending in-degree (disconnected islands) are fine.


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """Partial-graph gradients (parity: paddle.grad,
    python/paddle/autograd/backward_mode.py + GeneralGrad
    paddle/fluid/eager/general_grad.h).

    Implemented by running the tape while redirecting leaf accumulation to a
    side table for the requested inputs.
    """
    from ..core.tensor import Tensor

    single_out = isinstance(outputs, Tensor)
    outputs = [outputs] if single_out else list(outputs)
    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)

    if retain_graph is None:
        retain_graph = create_graph

    capture = {id(t): None for t in inputs}
    run_backward(outputs, grad_outputs, retain_graph=bool(retain_graph),
                 capture=capture, write_leaf_grad=False,
                 create_graph=create_graph)
    results = []
    for t in inputs:
        g = capture[id(t)]
        if g is None and not allow_unused:
            raise RuntimeError(
                "One of the differentiated tensors appears unused in the "
                "graph; pass allow_unused=True to return None for it.")
        if g is None:
            results.append(None)
        elif isinstance(g, Tensor):
            results.append(g)   # create_graph: carries its own tape history
        else:
            results.append(Tensor._from_value(g))
    return results[0] if single_in else results
