"""Saved-tensor hooks (parity: python/paddle/autograd/saved_tensors_hooks.py).

The reference lets users intercept forward activations saved for backward
(e.g. to offload them to host).  Our residuals live inside JAX vjp closures,
so the hook surface is narrower: we expose the context manager for API
compatibility and apply pack/unpack to tensors explicitly saved through
PyLayerContext.save_for_backward.
"""
from __future__ import annotations

import contextlib

_HOOKS = []


class saved_tensors_hooks(contextlib.AbstractContextManager):
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _HOOKS.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _HOOKS.pop()
        return False


def current_hooks():
    return _HOOKS[-1] if _HOOKS else None
