"""User-defined autograd ops.

Parity: paddle.autograd.PyLayer (reference:
python/paddle/autograd/py_layer.py:29, C++ side
paddle/fluid/eager/pylayer/).  The user supplies forward/backward static
methods; forward runs eagerly, backward is spliced into the tape as a
GradNode whose "vjp" calls the user function.
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from ..core.tensor import Tensor
from . import tape as _tape


class PyLayerContext:
    def __init__(self):
        self._saved: List[Any] = []
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _tape.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        single = isinstance(outs, Tensor)
        out_list = [outs] if single else list(outs)

        # edges for ALL tensor args, in forward-argument order — the user's
        # backward returns one grad per forward tensor input (parity:
        # python/paddle/autograd/py_layer.py); the engine prunes
        # stop_gradient edges itself.
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        any_grad = any(not t.stop_gradient for t in in_tensors)
        if _tape.is_grad_enabled() and any_grad:
            tensor_outs = [o for o in out_list if isinstance(o, Tensor)]
            out_meta = [(tuple(o._value.shape), o._value.dtype)
                        for o in tensor_outs]

            def vjp_fn(cots):
                if not isinstance(cots, tuple):
                    cots = (cots,)
                cot_tensors = [Tensor._from_value(c) for c in cots]
                grads = cls.backward(ctx, *cot_tensors)
                if isinstance(grads, Tensor) or grads is None:
                    grads = (grads,)
                vals = tuple(
                    g._value if isinstance(g, Tensor) else g for g in grads)
                if len(vals) < len(in_tensors):
                    vals = vals + (None,) * (len(in_tensors) - len(vals))
                return vals[: len(in_tensors)]

            def tensor_vjp(cot_tensors, _n=len(in_tensors)):
                # create_graph path: user backward runs on live Tensors with
                # grad enabled, so its ops record tape nodes and second-order
                # flows through the custom layer naturally.
                grads = cls.backward(ctx, *cot_tensors)
                if isinstance(grads, Tensor) or grads is None:
                    grads = (grads,)
                grads = tuple(grads)
                if len(grads) < _n:
                    grads = grads + (None,) * (_n - len(grads))
                return grads[:_n]

            node = _tape.GradNode(cls.__name__, vjp_fn, in_tensors, out_meta,
                                  out_is_tuple=len(out_meta) > 1,
                                  tensor_vjp=tensor_vjp)
            i = 0
            for o in out_list:
                if isinstance(o, Tensor):
                    o._grad_node = node
                    o._out_index = i
                    o.stop_gradient = False
                    i += 1
        return outs if not single else out_list[0]


class LegacyPyLayer(PyLayer):
    pass
