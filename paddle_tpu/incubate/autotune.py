"""Kernel autotune: runtime config selection + cache.

Capability parity with the reference's kernel autotune layer
(paddle/phi/kernels/autotune/ — auto_tune_base.h AutoTuneBase::Run times
candidate kernels with a GPU timer and caches the winner keyed on the
input signature, cache.h AlgorithmsCache, switch_autotune.cc the on/off
switch) and the Python surface paddle.incubate.autotune.set_config.

TPU-native design: candidates are (block_q, block_k) tilings of Pallas
kernels (the analog of cuDNN algo choice).  Timing uses a warmup +
block_until_ready median, the winner is cached in-process keyed on
(kernel, shape-signature, dtype) and optionally persisted to a JSON file
so later processes skip the search — the analog of the reference's
serialized algorithm cache.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

__all__ = ["set_config", "autotune_enabled", "AlgorithmCache",
           "autotune_select", "flash_attention_candidates"]

_config = {
    "kernel": {"enable": False, "tuning_range": None},
    "cache_file": None,
}


def set_config(config=None):
    """Parity: paddle.incubate.autotune.set_config — accepts a dict or a
    JSON file path with a {"kernel": {"enable": ...}} section."""
    if config is None:
        _config["kernel"]["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    kernel = config.get("kernel", {})
    _config["kernel"]["enable"] = bool(kernel.get("enable", False))
    if "tuning_range" in kernel:
        _config["kernel"]["tuning_range"] = kernel["tuning_range"]
    if "cache_file" in config:
        _config["cache_file"] = config["cache_file"]


def autotune_enabled() -> bool:
    return bool(_config["kernel"]["enable"])


class AlgorithmCache:
    """Winner cache (parity: autotune/cache.h AlgorithmsCache) with
    optional JSON persistence."""

    def __init__(self):
        self._cache: Dict[str, Any] = {}
        self._loaded_file: Optional[str] = None

    def _maybe_load(self):
        path = _config["cache_file"]
        if path and path != self._loaded_file and os.path.exists(path):
            try:
                with open(path) as f:
                    self._cache.update(json.load(f))
            except (OSError, ValueError):
                pass
            self._loaded_file = path

    def get(self, key: str):
        self._maybe_load()
        return self._cache.get(key)

    def put(self, key: str, value):
        self._cache[key] = value
        path = _config["cache_file"]
        if path:
            try:
                with open(path, "w") as f:
                    json.dump(self._cache, f)
            except OSError:
                pass

    def clear(self):
        self._cache.clear()


_cache = AlgorithmCache()


def _time_once(fn: Callable[[], Any]) -> float:
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def autotune_select(kernel_name: str, sig: Tuple,
                    candidates: Sequence[Any],
                    runner: Callable[[Any], Callable[[], Any]],
                    default: Any):
    """Pick the fastest candidate config for (kernel_name, sig).

    ``runner(cand)`` returns a zero-arg callable executing the kernel with
    that config; invalid configs may raise and are skipped (parity:
    AutoTuneBase::Run's per-algo try loop).  Off switch → ``default``.
    """
    if not autotune_enabled():
        return default
    key = f"{kernel_name}::{sig}"
    hit = _cache.get(key)
    if hit is not None:
        return tuple(hit) if isinstance(hit, list) else hit
    best, best_t = default, float("inf")
    for cand in candidates:
        try:
            fn = runner(cand)
            dt = min(_time_once(fn) for _ in range(2))
        except Exception:
            continue
        if dt < best_t:
            best, best_t = cand, dt
    _cache.put(key, list(best) if isinstance(best, tuple) else best)
    return best


def autotune_lookup(kernel_name: str, sig: Tuple):
    """Cache peek without searching — safe inside a jax trace (timing a
    candidate needs concrete buffers)."""
    if not autotune_enabled():
        return None
    hit = _cache.get(f"{kernel_name}::{sig}")
    return tuple(hit) if isinstance(hit, list) else hit


def flash_attention_candidates(seq_q: int, seq_k: int) -> List[Tuple[int,
                                                                     int]]:
    """(block_q, block_k) tilings that divide the sequence lengths —
    multiples of the 128-lane TPU tile, block_q up to 1024 (a resident
    q tile amortizes across the streamed k axis; 1024x512 measured best
    for D=128 on v5e), block_k capped at 512 (larger k blocks lost in
    every sweep and 2048x1024 exceeds the 16M VMEM budget)."""
    outs = []
    for bq in (128, 256, 512, 1024):
        for bk in (128, 256, 512):
            if bq <= seq_q and bk <= seq_k and seq_q % bq == 0 \
                    and seq_k % bk == 0:
                outs.append((bq, bk))
    return outs or [(min(128, seq_q), min(128, seq_k))]
