"""paddle_tpu.incubate — experimental APIs (parity: python/paddle/incubate)."""
from . import distributed
from . import nn
from . import optimizer
from . import autotune
from .optimizer import LookAhead, ModelAverage
