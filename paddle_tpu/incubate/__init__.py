"""paddle_tpu.incubate — experimental APIs (parity: python/paddle/incubate)."""
from . import distributed
from . import nn
from . import optimizer
from . import autotune
from .optimizer import LookAhead, ModelAverage


# -- round-4 incubate surface (parity: python/paddle/incubate/__init__.py) --
from ..geometric import (segment_sum, segment_mean, segment_max,  # noqa
                         segment_min)
from ..geometric import send_u_recv as _send_u_recv


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Parity: paddle.incubate.graph_send_recv (renamed send_u_recv in
    newer APIs — same gather-scatter message passing)."""
    return _send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                        out_size=out_size)


def softmax_mask_fuse(x, mask, name=None):
    """Parity: incubate.softmax_mask_fuse — softmax(x + mask) fused by
    XLA (one kernel on TPU; the reference hand-writes the fusion)."""
    from ..core.dispatch import apply_op
    import jax.numpy as jnp
    from ..ops._helpers import targ

    def fn(v, m):
        return jax.nn.softmax(v + m, axis=-1)

    import jax
    return apply_op("softmax_mask_fuse", fn, (x, targ(mask)))


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Parity: incubate.softmax_mask_fuse_upper_triangle — causal-masked
    softmax (upper triangle masked out)."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def fn(v):
        S = v.shape[-1]
        rows = jnp.arange(v.shape[-2])[:, None]
        cols = jnp.arange(S)[None, :]
        masked = jnp.where(rows >= cols, v, -1e9)
        return jax.nn.softmax(masked, axis=-1)

    return apply_op("softmax_mask_fuse_upper_triangle", fn, (x,))


def identity_loss(x, reduction="none"):
    """Parity: incubate.identity_loss."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    return x


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Parity: incubate.graph_khop_sampler — multi-hop neighbor sampling
    over a CSC graph (eager host sampling; graphs are host data)."""
    import numpy as np
    from ..core.tensor import Tensor as _T

    rowv = np.asarray(row._value if hasattr(row, "_value") else row)
    colp = np.asarray(colptr._value if hasattr(colptr, "_value")
                      else colptr)
    nodes = np.asarray(input_nodes._value
                       if hasattr(input_nodes, "_value")
                       else input_nodes).reshape(-1)
    rng = np.random.RandomState(0)
    edge_src, edge_dst = [], []
    frontier = nodes
    seen = list(nodes)
    for k in sample_sizes:
        nxt = []
        for n in frontier:
            beg, end = int(colp[n]), int(colp[n + 1])
            neigh = rowv[beg:end]
            if len(neigh) > k:
                neigh = rng.choice(neigh, k, replace=False)
            for m in neigh:
                edge_src.append(int(m))
                edge_dst.append(int(n))
                nxt.append(int(m))
        frontier = np.unique(np.asarray(nxt, np.int64)) \
            if nxt else np.zeros((0,), np.int64)
        seen.extend(frontier.tolist())
    uniq, inv = np.unique(np.asarray(
        list(nodes) + edge_src, np.int64), return_inverse=True)
    reindex_src = inv[len(nodes):]
    remap = {int(v): i for i, v in enumerate(uniq)}
    reindex_dst = np.asarray([remap[d] for d in edge_dst], np.int64)
    return (_T(reindex_src), _T(reindex_dst), _T(uniq),
            _T(np.asarray(edge_src, np.int64)))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Parity: incubate.graph_sample_neighbors — one-hop sampling."""
    import numpy as np
    from ..core.tensor import Tensor as _T
    rowv = np.asarray(row._value if hasattr(row, "_value") else row)
    colp = np.asarray(colptr._value if hasattr(colptr, "_value")
                      else colptr)
    nodes = np.asarray(input_nodes._value
                       if hasattr(input_nodes, "_value")
                       else input_nodes).reshape(-1)
    rng = np.random.RandomState(0)
    out_n, out_count = [], []
    for n in nodes:
        beg, end = int(colp[n]), int(colp[n + 1])
        neigh = rowv[beg:end]
        if sample_size > 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, sample_size, replace=False)
        out_n.extend(int(m) for m in neigh)
        out_count.append(len(neigh))
    return (_T(np.asarray(out_n, np.int64)),
            _T(np.asarray(out_count, np.int64)))


def graph_reindex(x, neighbors, count, value_buffer=None,
                  index_buffer=None, flag_buffer_hashtable=False,
                  name=None):
    """Parity: incubate.graph_reindex — compact node ids to 0..n."""
    import numpy as np
    from ..core.tensor import Tensor as _T
    xs = np.asarray(x._value if hasattr(x, "_value") else x).reshape(-1)
    nb = np.asarray(neighbors._value if hasattr(neighbors, "_value")
                    else neighbors).reshape(-1)
    cnt = np.asarray(count._value if hasattr(count, "_value")
                     else count).reshape(-1)
    uniq = []
    seen = {}
    for v in list(xs) + list(nb):
        v = int(v)
        if v not in seen:
            seen[v] = len(uniq)
            uniq.append(v)
    re_nb = np.asarray([seen[int(v)] for v in nb], np.int64)
    dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (_T(re_nb), _T(dst), _T(np.asarray(uniq, np.int64)))
