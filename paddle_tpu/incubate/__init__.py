"""paddle_tpu.incubate — experimental APIs (parity: python/paddle/incubate)."""
from . import distributed
from . import nn
