"""paddle.incubate.optimizer.functional (parity:
python/paddle/incubate/optimizer/functional/ — minimize_bfgs /
minimize_lbfgs: functional quasi-Newton minimization of an objective
closure, returning (is_converge, num_func_calls, x, f, g))."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor

__all__ = ["minimize_bfgs", "minimize_lbfgs"]


def _wolfe_line_search(f_g, xk, pk, fk, gk, max_iters=50):
    """Strong-Wolfe line search (same recipe the reference's
    line_search_wolfe uses)."""
    c1, c2 = 1e-4, 0.9
    alpha, prev_alpha, prev_f = 1.0, 0.0, fk
    calls = 0
    lo, hi = 0.0, None
    for _ in range(max_iters):
        fx, gx = f_g(xk + alpha * pk)
        calls += 1
        if fx > fk + c1 * alpha * float(gk @ pk) or fx >= prev_f:
            hi = alpha
        else:
            d = float(gx @ pk)
            if abs(d) <= -c2 * float(gk @ pk):
                return alpha, fx, gx, calls
            if d >= 0:
                hi = alpha
            else:
                lo = alpha
        alpha = (lo + hi) / 2.0 if hi is not None else alpha * 2.0
        prev_f = fx
    fx, gx = f_g(xk + alpha * pk)
    return alpha, fx, gx, calls + 1


def _prep(objective_func, initial_position):
    x0 = initial_position._value if isinstance(initial_position, Tensor) \
        else jnp.asarray(initial_position)

    def f_g(x):
        t = Tensor._from_value(x)
        t.stop_gradient = False
        y = objective_func(t)
        from ....autograd.tape import grad as _grad
        g = _grad([y], [t])
        g = g[0] if isinstance(g, list) else g
        return float(np.asarray(y._value)), jnp.asarray(g._value)

    return x0.astype(jnp.float32), f_g


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-7, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None, line_search_fn
                  ="strong_wolfe", dtype="float32", name=None):
    """Parity: functional/bfgs.py minimize_bfgs."""
    x, f_g = _prep(objective_func, initial_position)
    n = x.size
    H = jnp.eye(n) if initial_inverse_hessian_estimate is None else \
        jnp.asarray(initial_inverse_hessian_estimate._value
                    if isinstance(initial_inverse_hessian_estimate,
                                  Tensor)
                    else initial_inverse_hessian_estimate)
    fk, gk = f_g(x)
    calls = 1
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(gk))) < tolerance_grad:
            converged = True
            break
        p = -(H @ gk)
        alpha, fn, gn, c = _wolfe_line_search(f_g, x, p, fk, gk)
        calls += c
        s = alpha * p
        y = gn - gk
        sy = float(s @ y)
        if abs(float(jnp.max(jnp.abs(s)))) < tolerance_change:
            converged = True
            x, fk, gk = x + s, fn, gn
            break
        if sy > 1e-10:
            rho = 1.0 / sy
            I = jnp.eye(n)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        x, fk, gk = x + s, fn, gn
    if float(jnp.max(jnp.abs(gk))) < tolerance_grad:
        converged = True
    return (Tensor._from_value(jnp.asarray(converged)),
            Tensor._from_value(jnp.asarray(calls)),
            Tensor._from_value(x),
            Tensor._from_value(jnp.asarray(fk, jnp.float32)),
            Tensor._from_value(gk))


def minimize_lbfgs(objective_func, initial_position, history_size=100,
                   max_iters=50, tolerance_grad=1e-7,
                   tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None,
                   line_search_fn="strong_wolfe", dtype="float32",
                   name=None):
    """Parity: functional/lbfgs.py minimize_lbfgs (two-loop recursion)."""
    x, f_g = _prep(objective_func, initial_position)
    fk, gk = f_g(x)
    calls = 1
    S, Y = [], []
    converged = False
    for _ in range(max_iters):
        if float(jnp.max(jnp.abs(gk))) < tolerance_grad:
            converged = True
            break
        q = gk
        alphas = []
        for s, y in reversed(list(zip(S, Y))):
            rho = 1.0 / float(s @ y)
            a = rho * float(s @ q)
            alphas.append((a, rho, s, y))
            q = q - a * y
        gamma = (float(S[-1] @ Y[-1]) / float(Y[-1] @ Y[-1])) \
            if S else 1.0
        r = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(y @ r)
            r = r + (a - b) * s
        p = -r
        alpha, fn, gn, c = _wolfe_line_search(f_g, x, p, fk, gk)
        calls += c
        s = alpha * p
        y = gn - gk
        if abs(float(jnp.max(jnp.abs(s)))) < tolerance_change:
            converged = True
            x, fk, gk = x + s, fn, gn
            break
        if float(s @ y) > 1e-10:
            S.append(s)
            Y.append(y)
            if len(S) > history_size:
                S.pop(0)
                Y.pop(0)
        x, fk, gk = x + s, fn, gn
    if float(jnp.max(jnp.abs(gk))) < tolerance_grad:
        converged = True
    return (Tensor._from_value(jnp.asarray(converged)),
            Tensor._from_value(jnp.asarray(calls)),
            Tensor._from_value(x),
            Tensor._from_value(jnp.asarray(fk, jnp.float32)),
            Tensor._from_value(gk))
