"""Incubate optimizers (parity: python/paddle/incubate/optimizer/ —
LookAhead lookahead.py, ModelAverage modelaverage.py).

Both are wrappers over an inner optimizer; state lives as jax arrays so
the slow/averaged copies stay on device (HBM) and updates are fused jit
calls rather than per-parameter host loops.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd.tape import no_grad

from ...optimizer.lbfgs import LBFGS
from . import functional

__all__ = ["LookAhead", "ModelAverage", "LBFGS"]


class LookAhead:
    """k-step lookahead: slow weights track fast weights
    (parity: paddle.incubate.LookAhead, lookahead.py).

    Every ``k`` inner steps: slow += alpha * (fast - slow); fast = slow.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._step_num = 0
        self._parameter_list = inner_optimizer._parameter_list
        # slow weights snapshot the params at wrapper creation (reference
        # lookahead.py initializes slow_param from param on first step)
        self._slow: Dict[int, jnp.ndarray] = {
            id(p): p._value for p in self._parameter_list
            if not p.stop_gradient}

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k != 0:
            return
        a = self.alpha
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            slow = self._slow.get(id(p), p._value)
            slow = slow + a * (p._value - slow)
            self._slow[id(p)] = slow
            p._value = slow

    def clear_grad(self, *a, **k):
        self.inner_optimizer.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        out = self.inner_optimizer.state_dict()
        out["@lookahead_step"] = self._step_num
        for i, p in enumerate(self._parameter_list):
            if id(p) in self._slow:
                out[f"{p.name}_slow"] = Tensor._from_value(
                    self._slow[id(p)])
        return out

    def set_state_dict(self, state):
        self._step_num = int(state.pop("@lookahead_step", 0))
        for p in self._parameter_list:
            key = f"{p.name}_slow"
            if key in state:
                v = state.pop(key)
                self._slow[id(p)] = v._value if isinstance(v, Tensor) \
                    else jnp.asarray(v)
        self.inner_optimizer.set_state_dict(state)

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


class ModelAverage:
    """Running average of parameters for evaluation
    (parity: paddle.incubate.ModelAverage, modelaverage.py).

    Accumulates sums of parameter values over steps; ``apply()`` swaps the
    averaged weights in (optionally restoring with ``restore()``).  The
    reference's num_accumulates/old_num_accumulates windowing
    (min_average_window/max_average_window) is preserved.
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = [p for p in (parameters or [])]
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._sum_1: Dict[int, jnp.ndarray] = {}
        self._sum_2: Dict[int, jnp.ndarray] = {}
        self._sum_3: Dict[int, jnp.ndarray] = {}
        self._num_accumulates = 0
        self._old_num_accumulates = 0
        self._num_updates = 0
        self._backup: Dict[int, jnp.ndarray] = {}

    @no_grad()
    def step(self):
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            pid = id(p)
            z = jnp.zeros_like(p._value)
            self._sum_1.setdefault(pid, z)
            self._sum_2.setdefault(pid, z)
            self._sum_3.setdefault(pid, z)
            self._sum_1[pid] = self._sum_1[pid] + p._value
        self._num_accumulates += 1
        self._num_updates += 1
        if self._num_accumulates >= self.max_average_window or \
                self._num_accumulates >= self.average_window * \
                self._num_updates:
            for pid in self._sum_1:
                self._sum_2[pid] = self._sum_2[pid] + self._sum_1[pid] + \
                    self._sum_3[pid]
                self._sum_3[pid] = jnp.zeros_like(self._sum_2[pid])
                self._sum_1[pid] = jnp.zeros_like(self._sum_2[pid])
            self._old_num_accumulates += self._num_accumulates
            self._num_accumulates = 0

    def _averaged(self, p):
        pid = id(p)
        total = self._sum_1.get(pid, 0) + self._sum_2.get(pid, 0) + \
            self._sum_3.get(pid, 0)
        n = self._num_accumulates + self._old_num_accumulates
        if n == 0:
            return p._value
        return (total / n).astype(p._value.dtype)

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        for p in self._parameter_list:
            self._backup[id(p)] = p._value
            p._value = self._averaged(p)
        self._need_restore = need_restore
        return _ApplyGuard(self)

    @no_grad()
    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))


class _ApplyGuard:
    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self._ma, "_need_restore", True):
            self._ma.restore()
        return False
