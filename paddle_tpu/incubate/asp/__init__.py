"""Automatic SParsity (2:4 structured sparsity).

Parity: python/paddle/incubate/asp/asp.py (reference — mask generation
utils.py get_mask_1d/2d_greedy/best, prune_model, decorate, and the
supported-layer registry supported_layer_list.py).

TPU-native: masks are plain arrays multiplied into weights; the sparse
speedup itself is future XLA/sparsity work — what this module guarantees
(like the reference on non-Ampere hardware) is N:M PATTERN correctness:
pruned training keeps the mask through optimizer steps.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

import numpy as np

from ...core.tensor import Tensor
from ...nn.layer_base import Layer
from ... import nn

__all__ = ["calculate_density", "check_mask_1d", "check_mask_2d",
           "create_mask", "get_mask_1d", "get_mask_2d_greedy",
           "get_mask_2d_best", "prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers", "ASPHelper", "MaskAlgo"]


def calculate_density(x) -> float:
    """Fraction of non-zeros (parity: asp.py calculate_density)."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def get_mask_1d(t: np.ndarray, n=2, m=4) -> np.ndarray:
    """Keep the n largest of every m consecutive elements (parity:
    utils.py get_mask_1d)."""
    flat = t.reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = np.abs(flat.reshape(-1, m))
    mask = np.zeros_like(groups, dtype=bool)
    idx = np.argsort(-groups, axis=1)[:, :n]
    np.put_along_axis(mask, idx, True, axis=1)
    mask = mask.reshape(-1)
    if pad:
        mask = mask[:-pad]
    return mask.reshape(t.shape).astype(t.dtype)


def check_mask_1d(t: np.ndarray, n=2, m=4) -> bool:
    flat = np.asarray(t).reshape(-1)
    pad = (-flat.size) % m
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
    groups = flat.reshape(-1, m)
    return bool(np.all(np.count_nonzero(groups, axis=1) <= n))


def get_mask_2d_greedy(t: np.ndarray, n=2, m=4) -> np.ndarray:
    """Greedy 2D n:m mask over m x m patches (parity:
    utils.py get_mask_2d_greedy)."""
    mat = np.asarray(t)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    mask = np.zeros_like(padded, dtype=bool)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            patch = padded[bi:bi + m, bj:bj + m]
            pmask = np.zeros((m, m), dtype=bool)
            order = np.argsort(-patch, axis=None)
            rows = np.zeros(m, np.int64)
            cols = np.zeros(m, np.int64)
            for flat_idx in order:
                r, c = divmod(int(flat_idx), m)
                if rows[r] < n and cols[c] < n:
                    pmask[r, c] = True
                    rows[r] += 1
                    cols[c] += 1
            mask[bi:bi + m, bj:bj + m] = pmask
    return mask[:h, :w].astype(mat.dtype)


def get_mask_2d_best(t: np.ndarray, n=2, m=4) -> np.ndarray:
    """Exhaustive best 2D mask for small m (parity: get_mask_2d_best);
    falls back to greedy for m > 4 (search space explodes)."""
    if m > 4:
        return get_mask_2d_greedy(t, n, m)
    mat = np.asarray(t)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(np.abs(mat), ((0, ph), (0, pw)))
    # all per-row n-of-m patterns
    patterns = [np.array(p) for p in itertools.product(
        *[[0, 1]] * m) if sum(p) == n]
    mask = np.zeros_like(padded, dtype=bool)
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            patch = padded[bi:bi + m, bj:bj + m]
            best, best_score = None, -1.0
            for combo in itertools.product(range(len(patterns)), repeat=m):
                pm = np.stack([patterns[i] for i in combo])
                if not np.all(pm.sum(0) <= n):
                    continue
                score = float((patch * pm).sum())
                if score > best_score:
                    best, best_score = pm, score
            mask[bi:bi + m, bj:bj + m] = best.astype(bool)
    return mask[:h, :w].astype(mat.dtype)


def check_mask_2d(t: np.ndarray, n=2, m=4) -> bool:
    mat = np.asarray(t)
    h, w = mat.shape
    ph, pw = (-h) % m, (-w) % m
    padded = np.pad(mat, ((0, ph), (0, pw)))
    for bi in range(0, padded.shape[0], m):
        for bj in range(0, padded.shape[1], m):
            patch = padded[bi:bi + m, bj:bj + m]
            nz_r = np.count_nonzero(patch, axis=1)
            nz_c = np.count_nonzero(patch, axis=0)
            if np.any(nz_r > n) or np.any(nz_c > n):
                return False
    return True


class MaskAlgo:
    MASK_1D = "mask_1d"
    MASK_2D_GREEDY = "mask_2d_greedy"
    MASK_2D_BEST = "mask_2d_best"


_MASK_FN = {MaskAlgo.MASK_1D: get_mask_1d,
            MaskAlgo.MASK_2D_GREEDY: get_mask_2d_greedy,
            MaskAlgo.MASK_2D_BEST: get_mask_2d_best}


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4):
    arr = np.asarray(tensor._value if isinstance(tensor, Tensor)
                     else tensor)
    shape = arr.shape
    mat = arr.reshape(shape[0], -1) if arr.ndim != 2 else arr
    mask = _MASK_FN[func_name](mat, n, m)
    return Tensor(mask.reshape(shape).astype(np.float32))


class ASPHelper:
    """Mask bookkeeping (parity: asp.py ASPHelper)."""

    _excluded: set = set()
    _masks: Dict[int, Tensor] = {}

    @classmethod
    def supported(cls, layer: Layer) -> bool:
        if isinstance(layer, (nn.Linear, nn.Conv2D)):
            return True
        name = type(layer).__name__.lower()
        return name in _SUPPORTED_LAYERS

    @classmethod
    def prunable_params(cls, model: Layer):
        out = []
        for name, sub in model.named_sublayers(include_self=True):
            if not cls.supported(sub):
                continue
            if any(name.startswith(e) for e in cls._excluded if e):
                continue
            w = getattr(sub, "weight", None)
            if w is not None and w._value.ndim >= 2:
                out.append(w)
        return out


def set_excluded_layers(param_names, main_program=None, model=None):
    ASPHelper._excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded.clear()


def prune_model(model: Layer, n=2, m=4, mask_algo=MaskAlgo.MASK_1D,
                with_mask=True):
    """Apply n:m masks to every supported layer's weight (parity:
    asp.py prune_model).  Layers registered via ``add_supported_layer``
    with a custom pruning function use it (must return
    (pruned_weight, mask) numpy arrays).  Returns {param_id: mask}."""
    masks = {}
    for lname, sub in model.named_sublayers(include_self=True):
        if not ASPHelper.supported(sub):
            continue
        if any(lname.startswith(e) for e in ASPHelper._excluded if e):
            continue
        w = getattr(sub, "weight", None)
        if w is None or w._value.ndim < 2:
            continue
        custom = _custom_pruning_func(sub)
        if custom is not None:
            pruned, mask_arr = custom(np.asarray(w._value), n, m)
            from ...core.tensor import Tensor as _T
            mask = _T(np.asarray(mask_arr))
            w.set_value(np.asarray(pruned))
        else:
            mask = create_mask(w, mask_algo, n, m)
            w.set_value(np.asarray(w._value) * np.asarray(mask._value))
        masks[id(w)] = mask
        if with_mask:
            ASPHelper._masks[id(w)] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """Re-applies masks after every step (parity: asp.py decorate —
    the reference multiplies masks into params post-update)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        self._optimizer.step()
        for p in self._optimizer._parameter_list:
            mask = ASPHelper._masks.get(id(p))
            if mask is not None:
                p.set_value(np.asarray(p._value)
                            * np.asarray(mask._value))

    def clear_grad(self, *a, **k):
        self._optimizer.clear_grad(*a, **k)


def decorate(optimizer):
    return OptimizerWithSparsityGuarantee(optimizer)


def add_supported_layer(layer, pruning_func=None):
    """Parity: incubate/asp/supported_layer_list.py add_supported_layer —
    register a layer type (or name) whose weights ASP should prune, with
    an optional custom pruning function (weight, mask) consulted by
    prune_model via ``_SUPPORTED_LAYERS``."""
    name = (layer if isinstance(layer, str)
            else getattr(layer, "__name__", str(layer))).lower()
    _SUPPORTED_LAYERS[name] = pruning_func


def _custom_pruning_func(layer):
    return _SUPPORTED_LAYERS.get(type(layer).__name__.lower())


_SUPPORTED_LAYERS = {"linear": None, "conv2d": None}
__all__.append("add_supported_layer")
