"""paddle_tpu.incubate.nn — fused-op surfaces (parity:
python/paddle/incubate/nn — the Python face of the reference's fused
kernels #17)."""
from . import functional
from .layer import (FusedMultiHeadAttention, FusedFeedForward,
                    FusedTransformerEncoderLayer, FusedLinear,
                    FusedRMSNorm, FusedEcMoe, FusedDropoutAdd,
                    FusedBiasDropoutResidualLayerNorm,
                    FusedMultiTransformer)
