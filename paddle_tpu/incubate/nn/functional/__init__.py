"""Fused functional ops.

Parity: python/paddle/incubate/nn/functional/ (reference — the wrappers over
paddle/phi/kernels/fusion/: fused_rms_norm, fused_rotary_position_embedding,
fused_layer_norm, fused_matmul_bias, swiglu, masked/block attention).

TPU-native: "fused" means one XLA fusion / one Pallas kernel.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op
from ....core.tensor import Tensor
from ....ops._helpers import targ
from ....nn.functional.norm import rms_norm as _rms_norm
from ....nn.functional.norm import layer_norm as _layer_norm
from ....nn.functional.activation import swiglu  # noqa: F401
from ....nn.functional.common import (scaled_dot_product_attention,
                                      flash_attention)  # noqa: F401


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """Parity: fused_rms_norm (reference fused op #17)."""
    out = _rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out, None


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=1, **kw):
    shape = [int(s) for s in x.shape[begin_norm_axis:]]
    return _layer_norm(x, shape, norm_weight, norm_bias, epsilon), None, None


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    def fn(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if bb:
            out = out + bb[0]
        return out
    args = (x, targ(y)) + ((targ(bias),) if bias is not None else ())
    return apply_op("fused_matmul_bias", fn, args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style
                                    =True, time_major=False, rotary_emb_base
                                    =10000.0):
    """Parity: fused_rotary_position_embedding (reference #17).
    q/k/v: [batch, seq, heads, head_dim]."""
    def rope_one(t, sin_v, cos_v):
        if use_neox_rotary_style:
            half = t.shape[-1] // 2
            t1, t2 = t[..., :half], t[..., half:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., ::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_v + rot * sin_v

    def fn(*vals):
        i = 0
        qq = vals[i]; i += 1
        kk = vals[i] if k is not None else None
        i += 1 if k is not None else 0
        vv = vals[i] if v is not None else None
        i += 1 if v is not None else 0
        seq = qq.shape[1]
        dim = qq.shape[-1]
        if sin is None:
            if position_ids is not None:
                pos = vals[-1]                      # [S] or [B, S]
                if pos.ndim == 1:
                    pos = pos[:, None]              # [S, 1]
                    batched = False
                else:
                    pos = pos[..., None]            # [B, S, 1]
                    batched = True
            else:
                pos = jnp.arange(seq)[:, None]
                batched = False
            inv = 1.0 / (rotary_emb_base **
                         (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
            freqs = pos.astype(jnp.float32) * inv
            emb = jnp.concatenate([freqs, freqs], axis=-1)
            if batched:                              # [B, S, dim]
                sin_v = jnp.sin(emb)[:, :, None, :]
                cos_v = jnp.cos(emb)[:, :, None, :]
            else:
                sin_v = jnp.sin(emb)[None, :, None, :]
                cos_v = jnp.cos(emb)[None, :, None, :]
        else:
            sin_v = vals[i]; i += 1
            cos_v = vals[i]; i += 1
            if position_ids is not None and sin_v.ndim == 2:
                # [max_seq, dim] tables; position_ids selects rows
                pos = vals[-1]
                sin_v = jnp.take(sin_v, pos, axis=0)
                cos_v = jnp.take(cos_v, pos, axis=0)
                if pos.ndim == 2:        # [B, S, dim] -> [B, S, 1, dim]
                    sin_v = sin_v[:, :, None, :]
                    cos_v = cos_v[:, :, None, :]
                else:                    # [S, dim] -> [1, S, 1, dim]
                    sin_v = sin_v[None, :, None, :]
                    cos_v = cos_v[None, :, None, :]
            elif sin_v.ndim == 2:
                sin_v = sin_v[None, :, None, :]
                cos_v = cos_v[None, :, None, :]
        sin_v = sin_v.astype(jnp.float32)
        cos_v = cos_v.astype(jnp.float32)
        outs = [rope_one(qq.astype(jnp.float32), sin_v,
                         cos_v).astype(qq.dtype)]
        if kk is not None:
            outs.append(rope_one(kk.astype(jnp.float32), sin_v,
                                 cos_v).astype(kk.dtype))
        if vv is not None:
            outs.append(vv)
        return tuple(outs) if len(outs) > 1 else outs[0]

    args = [q]
    if k is not None:
        args.append(targ(k))
    if v is not None:
        args.append(targ(v))
    if sin is not None:
        args += [targ(sin), targ(cos)]
    if position_ids is not None:
        args.append(targ(position_ids))
    out = apply_op("fused_rope", fn, tuple(args))
    if k is None and v is None:
        return out, None, None
    outs = list(out) if isinstance(out, tuple) else [out]
    while len(outs) < 3:
        outs.append(None)
    return tuple(outs[:3])


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p, training=training, mode=mode) + y


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, **kw):
    from ....nn.functional.common import dropout
    out = x if bias is None else x + bias
    out = dropout(out, dropout_rate, training=training) + residual
    shape = [int(out.shape[-1])]
    return _layer_norm(out, shape, ln_scale, ln_bias, ln_epsilon)


# serving fused set (reference phi/kernels/fusion — paged/dense decode
# attention); implementations live with the pallas kernels
from ....ops.paged_attention import (block_multihead_attention,  # noqa: E402,F401
                                     masked_multihead_attention,
                                     paged_attention)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, name=None):
    """Ragged-batch attention (parity:
    paddle.incubate.nn.functional.variable_length_memory_efficient_attention,
    reference kernel paddle/phi/kernels/fusion/
    variable_length_memory_efficient_attention — per-sequence q/kv
    lengths, memory-efficient streaming softmax).

    Layout [B, H, S, D] (reference layout for this op); ``seq_lens`` /
    ``kv_seq_lens`` are [B] int tensors with each sequence's true
    length.  TPU-native: padding positions are masked with a built
    length mask and the chunked online-softmax path keeps memory
    O(S·D); fully-padded query rows return 0.
    """
    from ....ops.pallas_kernels import _chunked_sdpa
    from ....ops._helpers import as_value

    q_lens = as_value(seq_lens).reshape(-1).astype(jnp.int32)
    k_lens = as_value(kv_seq_lens).reshape(-1).astype(jnp.int32)
    if scale is None:
        scale = 1.0 / math.sqrt(int(query.shape[-1]))
    rescale = scale * math.sqrt(int(query.shape[-1]))  # vs default 1/sqrt(d)

    def fn(q, k, v, *m):
        B, H, Sq, D = q.shape
        Sk = k.shape[2]
        rows_ok = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, 1), 2) \
            < q_lens[:, None, None, None]
        cols_ok = jax.lax.broadcasted_iota(jnp.int32, (B, 1, 1, Sk), 3) \
            < k_lens[:, None, None, None]
        length_mask = jnp.broadcast_to(rows_ok & cols_ok,
                                       (B, 1, Sq, Sk))
        # padded query rows attend a single dummy column so their
        # softmax stays well-defined (no -inf row → no NaN cotangents
        # in the backward); the rows are zeroed below regardless
        first_col = jax.lax.broadcasted_iota(
            jnp.int32, (B, 1, Sq, Sk), 3) == 0
        if causal:
            # per-sequence bottom-right alignment: row i of sequence b
            # attends cols j <= i + (kv_len_b - q_len_b) — the padded
            # buffer shapes must NOT define causality (decode-with-cache
            # has q_len < kv_len inside same-size buffers)
            rows_i = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, Sk),
                                              2)
            cols_j = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, Sk),
                                              3)
            off = (k_lens - q_lens)[:, None, None, None]
            length_mask = length_mask & (cols_j <= rows_i + off)
        length_mask = length_mask | (~rows_ok & first_col)
        if m:
            extra = m[0]
            if extra.dtype == jnp.bool_:
                length_mask = length_mask & extra
                extra_add = None
            else:
                extra_add = extra
        else:
            extra_add = None
        qv = (q * rescale).astype(q.dtype) if rescale != 1.0 else q
        if extra_add is not None:
            # compose additive user mask with the length mask so one
            # chunked pass applies both
            mask_final = jnp.where(length_mask, 0.0, -1e30) + extra_add
        else:
            mask_final = length_mask
        # causality is already inside mask_final (true-length aligned);
        # the chunked kernel's causal flag would align to buffer shapes
        out = _chunked_sdpa(qv, k, v, False, mask=mask_final)
        # zero out padded query rows (they attended the dummy column)
        return jnp.where(rows_ok, out, 0).astype(q.dtype)

    args = (query, targ(key), targ(value))
    if mask is not None:
        args = args + (targ(mask),)
    return apply_op("variable_length_memory_efficient_attention", fn,
                    args)
