"""Fused functional ops.

Parity: python/paddle/incubate/nn/functional/ (reference — the wrappers over
paddle/phi/kernels/fusion/: fused_rms_norm, fused_rotary_position_embedding,
fused_layer_norm, fused_matmul_bias, swiglu, masked/block attention).

TPU-native: "fused" means one XLA fusion / one Pallas kernel.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.dispatch import apply_op
from ....core.tensor import Tensor
from ....ops._helpers import targ
from ....nn.functional.norm import rms_norm as _rms_norm
from ....nn.functional.norm import layer_norm as _layer_norm
from ....nn.functional.activation import swiglu  # noqa: F401
from ....nn.functional.common import (scaled_dot_product_attention,
                                      flash_attention)  # noqa: F401
from ....nn.functional.common import dropout as _dropout
from ....nn.functional.activation import (relu as _ff_relu,
                                          gelu as _ff_gelu)
from ....ops import add as _add
from ....ops.linalg import matmul as _mm


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    """Parity: fused_rms_norm (reference fused op #17)."""
    out = _rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out, None


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=1, **kw):
    shape = [int(s) for s in x.shape[begin_norm_axis:]]
    return _layer_norm(x, shape, norm_weight, norm_bias, epsilon), None, None


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    def fn(a, b, *bb):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = jnp.matmul(a, b)
        if bb:
            out = out + bb[0]
        return out
    args = (x, targ(y)) + ((targ(bias),) if bias is not None else ())
    return apply_op("fused_matmul_bias", fn, args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, transpose_y=transpose_weight)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style
                                    =True, time_major=False, rotary_emb_base
                                    =10000.0):
    """Parity: fused_rotary_position_embedding (reference #17).
    q/k/v: [batch, seq, heads, head_dim]."""
    def rope_one(t, sin_v, cos_v):
        if use_neox_rotary_style:
            half = t.shape[-1] // 2
            t1, t2 = t[..., :half], t[..., half:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
        else:
            t1 = t[..., ::2]
            t2 = t[..., 1::2]
            rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * cos_v + rot * sin_v

    def fn(*vals):
        i = 0
        qq = vals[i]; i += 1
        kk = vals[i] if k is not None else None
        i += 1 if k is not None else 0
        vv = vals[i] if v is not None else None
        i += 1 if v is not None else 0
        seq = qq.shape[1]
        dim = qq.shape[-1]
        if sin is None:
            if position_ids is not None:
                pos = vals[-1]                      # [S] or [B, S]
                if pos.ndim == 1:
                    pos = pos[:, None]              # [S, 1]
                    batched = False
                else:
                    pos = pos[..., None]            # [B, S, 1]
                    batched = True
            else:
                pos = jnp.arange(seq)[:, None]
                batched = False
            inv = 1.0 / (rotary_emb_base **
                         (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
            freqs = pos.astype(jnp.float32) * inv
            emb = jnp.concatenate([freqs, freqs], axis=-1)
            if batched:                              # [B, S, dim]
                sin_v = jnp.sin(emb)[:, :, None, :]
                cos_v = jnp.cos(emb)[:, :, None, :]
            else:
                sin_v = jnp.sin(emb)[None, :, None, :]
                cos_v = jnp.cos(emb)[None, :, None, :]
        else:
            sin_v = vals[i]; i += 1
            cos_v = vals[i]; i += 1
            if position_ids is not None and sin_v.ndim == 2:
                # [max_seq, dim] tables; position_ids selects rows
                pos = vals[-1]
                sin_v = jnp.take(sin_v, pos, axis=0)
                cos_v = jnp.take(cos_v, pos, axis=0)
                if pos.ndim == 2:        # [B, S, dim] -> [B, S, 1, dim]
                    sin_v = sin_v[:, :, None, :]
                    cos_v = cos_v[:, :, None, :]
                else:                    # [S, dim] -> [1, S, 1, dim]
                    sin_v = sin_v[None, :, None, :]
                    cos_v = cos_v[None, :, None, :]
            elif sin_v.ndim == 2:
                sin_v = sin_v[None, :, None, :]
                cos_v = cos_v[None, :, None, :]
        sin_v = sin_v.astype(jnp.float32)
        cos_v = cos_v.astype(jnp.float32)
        outs = [rope_one(qq.astype(jnp.float32), sin_v,
                         cos_v).astype(qq.dtype)]
        if kk is not None:
            outs.append(rope_one(kk.astype(jnp.float32), sin_v,
                                 cos_v).astype(kk.dtype))
        if vv is not None:
            outs.append(vv)
        return tuple(outs) if len(outs) > 1 else outs[0]

    args = [q]
    if k is not None:
        args.append(targ(k))
    if v is not None:
        args.append(targ(v))
    if sin is not None:
        args += [targ(sin), targ(cos)]
    if position_ids is not None:
        args.append(targ(position_ids))
    out = apply_op("fused_rope", fn, tuple(args))
    if k is None and v is None:
        return out, None, None
    outs = list(out) if isinstance(out, tuple) else [out]
    while len(outs) < 3:
        outs.append(None)
    return tuple(outs[:3])


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p, training=training, mode=mode) + y


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, **kw):
    from ....nn.functional.common import dropout
    out = x if bias is None else x + bias
    out = dropout(out, dropout_rate, training=training) + residual
    shape = [int(out.shape[-1])]
    return _layer_norm(out, shape, ln_scale, ln_bias, ln_epsilon)


# serving fused set (reference phi/kernels/fusion — paged/dense decode
# attention); implementations live with the pallas kernels
from ....ops.paged_attention import (block_multihead_attention,  # noqa: E402,F401
                                     masked_multihead_attention,
                                     paged_attention)


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, name=None):
    """Ragged-batch attention (parity:
    paddle.incubate.nn.functional.variable_length_memory_efficient_attention,
    reference kernel paddle/phi/kernels/fusion/
    variable_length_memory_efficient_attention — per-sequence q/kv
    lengths, memory-efficient streaming softmax).

    Layout [B, H, S, D] (reference layout for this op); ``seq_lens`` /
    ``kv_seq_lens`` are [B] int tensors with each sequence's true
    length.  TPU-native: padding positions are masked with a built
    length mask and the chunked online-softmax path keeps memory
    O(S·D); fully-padded query rows return 0.
    """
    from ....ops.pallas_kernels import _chunked_sdpa
    from ....ops._helpers import as_value

    q_lens = as_value(seq_lens).reshape(-1).astype(jnp.int32)
    k_lens = as_value(kv_seq_lens).reshape(-1).astype(jnp.int32)
    if scale is None:
        scale = 1.0 / math.sqrt(int(query.shape[-1]))
    rescale = scale * math.sqrt(int(query.shape[-1]))  # vs default 1/sqrt(d)

    def fn(q, k, v, *m):
        B, H, Sq, D = q.shape
        Sk = k.shape[2]
        rows_ok = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, 1), 2) \
            < q_lens[:, None, None, None]
        cols_ok = jax.lax.broadcasted_iota(jnp.int32, (B, 1, 1, Sk), 3) \
            < k_lens[:, None, None, None]
        length_mask = jnp.broadcast_to(rows_ok & cols_ok,
                                       (B, 1, Sq, Sk))
        # padded query rows attend a single dummy column so their
        # softmax stays well-defined (no -inf row → no NaN cotangents
        # in the backward); the rows are zeroed below regardless
        first_col = jax.lax.broadcasted_iota(
            jnp.int32, (B, 1, Sq, Sk), 3) == 0
        if causal:
            # per-sequence bottom-right alignment: row i of sequence b
            # attends cols j <= i + (kv_len_b - q_len_b) — the padded
            # buffer shapes must NOT define causality (decode-with-cache
            # has q_len < kv_len inside same-size buffers)
            rows_i = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, Sk),
                                              2)
            cols_j = jax.lax.broadcasted_iota(jnp.int32, (B, 1, Sq, Sk),
                                              3)
            off = (k_lens - q_lens)[:, None, None, None]
            length_mask = length_mask & (cols_j <= rows_i + off)
        length_mask = length_mask | (~rows_ok & first_col)
        if m:
            extra = m[0]
            if extra.dtype == jnp.bool_:
                length_mask = length_mask & extra
                extra_add = None
            else:
                extra_add = extra
        else:
            extra_add = None
        qv = (q * rescale).astype(q.dtype) if rescale != 1.0 else q
        if extra_add is not None:
            # compose additive user mask with the length mask so one
            # chunked pass applies both
            mask_final = jnp.where(length_mask, 0.0, -1e30) + extra_add
        else:
            mask_final = length_mask
        # causality is already inside mask_final (true-length aligned);
        # the chunked kernel's causal flag would align to buffer shapes
        out = _chunked_sdpa(qv, k, v, False, mask=mask_final)
        # zero out padded query rows (they attended the dummy column)
        return jnp.where(rows_ok, out, 0).astype(q.dtype)

    args = (query, targ(key), targ(value))
    if mask is not None:
        args = args + (targ(mask),)
    return apply_op("variable_length_memory_efficient_attention", fn,
                    args)


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0, name=None):
    """Parity: paddle.incubate.nn.functional.fused_bias_act (phi
    fused_bias_act kernel): out = act(x + bias); the int8/smooth-quant
    arguments are inference-dequant knobs the TPU path does not use."""
    if dequant_scales is not None or shift is not None \
            or smooth is not None or quant_scale != -1:
        raise NotImplementedError(
            "fused_bias_act quantization arguments are not supported on "
            "the TPU path")
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "geglu": None, "swiglu": None}
    if act_method not in acts:
        raise ValueError(f"unsupported act_method {act_method!r}")

    def fn(v, *b):
        h = v + b[0] if b else v
        if act_method in ("geglu", "swiglu"):
            a, g = jnp.split(h, 2, axis=-1)
            inner = jax.nn.gelu(a.astype(jnp.float32)) \
                if act_method == "geglu" \
                else jax.nn.silu(a.astype(jnp.float32))
            return (inner * g.astype(jnp.float32)).astype(v.dtype)
        return acts[act_method](h.astype(jnp.float32)).astype(v.dtype)

    args = (x,) + ((targ(bias),) if bias is not None else ())
    return apply_op("fused_bias_act", fn, args)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """Parity: paddle.incubate.nn.functional.fused_linear_activation
    (cuBLASLt epilogue fusion in the reference) — one matmul with the
    bias+activation fused by XLA."""
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
            "none": lambda v: v, "": lambda v: v}
    if activation not in acts:
        raise ValueError(f"unsupported activation {activation!r}")

    def fn(a, w, *b):
        if trans_x:
            a = jnp.swapaxes(a, -1, -2)
        if trans_y:
            w = jnp.swapaxes(w, -1, -2)
        out = a @ w
        if b:
            out = out + b[0]
        return acts[activation](out.astype(jnp.float32)).astype(out.dtype)

    args = (x, targ(y)) + ((targ(bias),) if bias is not None else ())
    return apply_op("fused_linear_activation", fn, args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """Parity: python/paddle/incubate/nn/functional/fused_transformer.py:36
    — residual + (pre|post)-LN transformer FFN in one op."""
    act = {"relu": _ff_relu, "gelu": _ff_gelu}[activation]
    residual = x
    out = x
    if pre_layer_norm:
        out = _layer_norm(out, x.shape[-1], weight=ln1_scale, bias=ln1_bias,
                          epsilon=ln1_epsilon)
    h = _mm(out, linear1_weight)
    if linear1_bias is not None:
        h = _add(h, linear1_bias)
    h = _dropout(act(h), dropout1_rate, training=training, mode=mode)
    h = _mm(h, linear2_weight)
    if linear2_bias is not None:
        h = _add(h, linear2_bias)
    h = _dropout(h, dropout2_rate, training=training, mode=mode)
    if add_residual:
        h = _add(residual, h)
    if not pre_layer_norm:
        h = _layer_norm(h, h.shape[-1], weight=ln2_scale, bias=ln2_bias,
                        epsilon=ln2_epsilon)
    return h


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None,
                               cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Parity: python/paddle/incubate/nn/functional/fused_transformer.py:514
    — fused self-attention block (residual + LN + qkv + sdpa + out proj).
    qkv_weight: [3, num_heads, head_dim, d_model] (or [d_model, 3*d] with
    transpose_qkv_wb=True and num_heads given)."""
    residual = x
    out = x
    if pre_layer_norm:
        out = _layer_norm(out, x.shape[-1], weight=pre_ln_scale,
                          bias=pre_ln_bias, epsilon=pre_ln_epsilon)

    def qkv_fn(v, w, *b):
        B, S, D = v.shape
        if transpose_qkv_wb:
            if num_heads <= 0:
                raise ValueError("num_heads required with "
                                 "transpose_qkv_wb=True")
            h = v @ w                                    # [B,S,3*D]
            if b:
                h = h + b[0]
            h = h.reshape(B, S, 3, num_heads, D // num_heads)
        else:
            h = jnp.einsum("bsd,thed->bsthe", v, w)      # [B,S,3,H,hd]
            if b:
                h = h + b[0].reshape(1, 1, *b[0].shape)
        return h[:, :, 0], h[:, :, 1], h[:, :, 2]        # [B,S,H,hd]

    qkv_args = (out, targ(qkv_weight)) + (
        (targ(qkv_bias),) if qkv_bias is not None else ())
    q, k, v = apply_op("fused_mha_qkv", qkv_fn, qkv_args)

    if cache_kv is not None:
        from ....ops.manipulation import concat
        k = concat([cache_kv[0], k], axis=1)
        v = concat([cache_kv[1], v], axis=1)
        cache_out = (k, v)
    attn = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        is_causal=False, training=training)
    B, S = x.shape[0], x.shape[1]
    attn = attn.reshape([B, S, -1])
    out = _mm(attn, linear_weight)
    if linear_bias is not None:
        out = _add(out, linear_bias)
    out = _dropout(out, dropout_rate, training=training, mode=mode)
    if add_residual:
        out = _add(residual, out)
    if not pre_layer_norm:
        out = _layer_norm(out, out.shape[-1], weight=ln_scale,
                          bias=ln_bias, epsilon=ln_epsilon)
    if cache_kv is not None:
        return out, cache_out
    return out


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights,
                            qkv_biases, linear_weights, linear_biases,
                            ffn_ln_scales, ffn_ln_biases, ffn1_weights,
                            ffn1_biases, ffn2_weights, ffn2_biases,
                            pre_layer_norm=True, epsilon=1e-5,
                            cache_kvs=None, pre_caches=None, seq_lens=None,
                            rotary_embs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0,
                            rotary_emb_dims=0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """Parity: python/paddle/incubate/nn/functional/fused_transformer.py:976
    — the whole pre-LN transformer stack as one call (the reference's
    serving-path op).  Composes the fused MHA + FFN ops per layer; KV
    caches append per layer when given."""
    if pre_caches is not None or rotary_emb_dims:
        raise NotImplementedError(
            "fused_multi_transformer: pre_caches / rotary_emb_dims are "
            "not supported on this path (use the model-level generation "
            "APIs for rope + prefix cache)")
    if seq_lens is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer: seq_lens / time_step (padded-batch "
            "serving) are not supported on this path — use "
            "variable_length_memory_efficient_attention or the "
            "inference.Predictor generation loop")
    if not trans_qkvw:
        raise NotImplementedError(
            "fused_multi_transformer supports trans_qkvw=True "
            "([3, H, head_dim, D] qkv weights) only")
    if not pre_layer_norm:
        raise NotImplementedError(
            "fused_multi_transformer supports pre_layer_norm=True only "
            "(matching the reference kernel)")
    out = x
    new_caches = []
    n_layers = len(qkv_weights)

    def get(lst, i):
        return None if lst is None else lst[i]

    for i in range(n_layers):
        cache = None if cache_kvs is None else cache_kvs[i]
        res = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i], pre_layer_norm=True,
            pre_ln_scale=get(ln_scales, i), pre_ln_bias=get(ln_biases, i),
            pre_ln_epsilon=epsilon, qkv_bias=get(qkv_biases, i),
            linear_bias=get(linear_biases, i), cache_kv=cache,
            attn_mask=attn_mask, dropout_rate=dropout_rate,
            attn_dropout_rate=dropout_rate, training=training, mode=mode,
            add_residual=True, transpose_qkv_wb=False)
        if cache is not None:
            out, new_cache = res
            new_caches.append(new_cache)
        else:
            out = res
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i],
            linear1_bias=get(ffn1_biases, i),
            linear2_bias=get(ffn2_biases, i),
            ln1_scale=get(ffn_ln_scales, i),
            ln1_bias=get(ffn_ln_biases, i), ln1_epsilon=epsilon,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, pre_layer_norm=True,
            training=training, mode=mode, add_residual=True)
    if cache_kvs is not None:
        return out, new_caches
    return out


def fused_ec_moe(x, gate, bmm0_weight, bmm0_bias, bmm1_weight, bmm1_bias,
                 act_type="gelu", tokens_per_expert=None, name=None):
    """Parity: python/paddle/incubate/nn/functional/fused_ec_moe.py (phi
    cutlass moe_kernel.cu, which supports ec_route=True only).

    Expert-choice routing: each expert picks its top-C tokens by the
    softmax gate score (C = tokens_per_expert, default 2*S/E like the
    EC-MoE paper's capacity factor 2), runs its FFN on them, and the
    picks combine back weighted by the gate probability.  All experts
    run as batched einsums on the MXU."""
    if act_type not in ("gelu", "relu"):
        raise ValueError(f"unsupported act_type {act_type!r}")

    if tokens_per_expert is not None and tokens_per_expert < 1:
        raise ValueError("tokens_per_expert must be >= 1")

    def fn(v, g, w0, b0, w1, b1):
        B, S, D = v.shape
        E = g.shape[-1]
        C = tokens_per_expert if tokens_per_expert is not None \
            else max(1, 2 * S // E)
        C = min(C, S)          # an expert cannot pick more tokens than S
        probs = jax.nn.softmax(g.astype(jnp.float32), axis=-1)  # [B,S,E]
        # each expert picks its top-C tokens (per batch row)
        scores = jnp.swapaxes(probs, 1, 2)                # [B,E,S]
        top_w, top_i = jax.lax.top_k(scores, C)           # [B,E,C]
        picked = jnp.take_along_axis(
            v[:, None], top_i[..., None], axis=2)         # [B,E,C,D]
        h = jnp.einsum("becd,edm->becm", picked.astype(jnp.float32),
                       w0.astype(jnp.float32)) + b0[None, :, 0][:, :, None]
        h = jax.nn.gelu(h) if act_type == "gelu" else jax.nn.relu(h)
        eo = jnp.einsum("becm,emd->becd", h,
                        w1.astype(jnp.float32)) + b1[None, :, 0][:, :, None]
        # scatter-combine: out[token] += prob * expert_out
        out = jnp.zeros((B, S, D), jnp.float32)
        bidx = jnp.arange(B)[:, None, None]
        out = out.at[bidx, top_i].add(eo * top_w[..., None])
        return out.astype(v.dtype)

    return apply_op("fused_ec_moe", fn,
                    (x, targ(gate), targ(bmm0_weight), targ(bmm0_bias),
                     targ(bmm1_weight), targ(bmm1_bias)))
