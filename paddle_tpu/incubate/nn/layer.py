"""Fused layer classes (parity: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward,
FusedTransformerEncoderLayer, FusedLinear; fused_ec_moe.py FusedEcMoe).

TPU-native: the "fusion" is XLA's job — these classes exist so code
written against the reference's fused surfaces runs unchanged, while the
bodies route through the same SDPA/linear/norm ops the rest of the stack
uses (flash attention underneath, casts/bias adds fused by the
compiler).
"""
from __future__ import annotations

import math

import numpy as np

from ... import nn
from ...core.tensor import Tensor
from ...nn import functional as F
from . import functional as IF

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer", "FusedLinear", "FusedRMSNorm",
           "FusedEcMoe"]


class FusedMultiHeadAttention(nn.Layer):
    """Parity: incubate.nn.FusedMultiHeadAttention (pre/post-LN attention
    block with residual, dropout, and fused qkv projection)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if embed_dim % num_heads:
            raise ValueError("embed_dim must divide num_heads")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        # fused qkv: one [3E, E] projection (reference qkv_weight layout)
        self.qkv_proj = nn.Linear(embed_dim, 3 * embed_dim,
                                  weight_attr=qkv_weight_attr,
                                  bias_attr=qkv_bias_attr)
        self.out_proj = nn.Linear(embed_dim, embed_dim,
                                  weight_attr=linear_weight_attr,
                                  bias_attr=linear_bias_attr)
        self.pre_ln = nn.LayerNorm(embed_dim, epsilon)
        self.post_ln = nn.LayerNorm(embed_dim, epsilon)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        residual = query
        x = self.pre_ln(query) if self.normalize_before else query
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x).reshape([B, S, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate if self.training else 0.0,
            training=self.training)
        out = self.out_proj(out.reshape([B, S, self.embed_dim]))
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = residual + out
        if not self.normalize_before:
            out = self.post_ln(out)
        return out


class FusedFeedForward(nn.Layer):
    """Parity: incubate.nn.FusedFeedForward (LN + linear-act-linear with
    residual)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.activation = activation
        self.linear1 = nn.Linear(d_model, dim_feedforward,
                                 weight_attr=linear1_weight_attr,
                                 bias_attr=linear1_bias_attr)
        self.linear2 = nn.Linear(dim_feedforward, d_model,
                                 weight_attr=linear2_weight_attr,
                                 bias_attr=linear2_bias_attr)
        self.norm = nn.LayerNorm(d_model, epsilon)

    def forward(self, src, cache=None):
        residual = src
        x = self.norm(src) if self.normalize_before else src
        act = getattr(F, self.activation)
        x = act(self.linear1(x))
        x = F.dropout(x, self.act_dropout_rate, training=self.training)
        x = self.linear2(x)
        x = F.dropout(x, self.dropout_rate, training=self.training)
        out = residual + x
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedTransformerEncoderLayer(nn.Layer):
    """Parity: incubate.nn.FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead,
            dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)


class FusedLinear(nn.Linear):
    """Parity: incubate.nn.FusedLinear — the matmul+bias epilogue fusion
    is XLA's default behavior, so this is nn.Linear with the reference's
    signature (transpose_weight kept for API parity)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__(in_features, out_features,
                         weight_attr=weight_attr, bias_attr=bias_attr)
        self._transpose_weight = transpose_weight

    def forward(self, x):
        return IF.fused_linear(x, self.weight, self.bias,
                               transpose_weight=self._transpose_weight)


class FusedRMSNorm(nn.Layer):
    """Parity surface for a fused RMSNorm layer over the Pallas/XLA
    rms_norm kernel."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.weight = self.create_parameter(
            [hidden_size],
            default_initializer=nn.initializer.Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x):
        out = IF.fused_rms_norm(x, self.weight, epsilon=self.epsilon)
        return out[0] if isinstance(out, tuple) else out


class FusedEcMoe(nn.Layer):
    """Parity: incubate.nn.FusedEcMoe (expert-choice MoE block:
    gate → per-expert two-layer FFN → weighted combine; reference
    python/paddle/incubate/nn/functional/fused_ec_moe.py).

    TPU-native: expert FFNs run as one batched einsum over the expert
    axis (MXU-friendly), not a per-expert loop."""

    def __init__(self, hidden_size, inter_size, num_experts,
                 act_type="gelu", weight_attr=None, bias_attr=None):
        super().__init__()
        if act_type not in ("gelu", "relu"):
            raise ValueError("act_type must be gelu or relu")
        self.act_type = act_type
        init = nn.initializer.XavierUniform()
        self.gate = nn.Linear(hidden_size, num_experts)
        self.w1 = self.create_parameter(
            [num_experts, hidden_size, inter_size],
            default_initializer=init)
        self.b1 = self.create_parameter(
            [num_experts, 1, inter_size],
            default_initializer=nn.initializer.Constant(0.0))
        self.w2 = self.create_parameter(
            [num_experts, inter_size, hidden_size],
            default_initializer=init)
        self.b2 = self.create_parameter(
            [num_experts, 1, hidden_size],
            default_initializer=nn.initializer.Constant(0.0))

    def forward(self, x, gate_weight=None):
        # delegate to the functional op (reference layout: the layer
        # wraps incubate.nn.functional.fused_ec_moe) so both surfaces
        # share one expert-choice routing implementation
        from .functional import fused_ec_moe
        return fused_ec_moe(x, self.gate(x), self.w1, self.b1,
                            self.w2, self.b2, act_type=self.act_type)


class FusedDropoutAdd(nn.Layer):
    """Parity: incubate.nn.FusedDropoutAdd — dropout(x) + y in one
    dispatched op (XLA fuses the mask-scale-add chain)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from ...nn import functional as F
        return F.dropout(x, p=self.p, training=self.training,
                         mode=self.mode) + y

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """Parity: incubate.nn.FusedBiasDropoutResidualLayerNorm —
    LN(residual + dropout(x + bias)); one fused region under XLA
    (reference kernel paddle/phi/kernels/fusion/
    fused_bias_dropout_residual_layer_norm)."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        from ...nn import initializer as I
        self._dropout_rate = dropout_rate
        self._epsilon = epsilon
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)

    def forward(self, x, residual):
        from ...nn import functional as F
        h = F.dropout(x + self.linear_bias, p=self._dropout_rate,
                      training=self.training)
        return F.layer_norm(residual + h,
                            normalized_shape=[x.shape[-1]],
                            weight=self.ln_scale, bias=self.ln_bias,
                            epsilon=self._epsilon)


class FusedMultiTransformer(nn.Layer):
    """Parity: incubate.nn.FusedMultiTransformer — the inference-side
    stacked transformer (reference fused_multi_transformer kernel,
    python/paddle/incubate/nn/layer/fused_transformer.py:1103): L
    pre-LN decoder layers in one module, optional per-layer KV caches
    for autoregressive decode.  Attention/FFN math runs the same fused
    paths as the serving engine (flash attention + swiglu/relu MLP
    fusion under XLA)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu",
                 normalize_before=True, ln_scale_attrs=None,
                 ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise NotImplementedError(
                "FusedMultiTransformer supports the pre-LN form "
                "(normalize_before=True), like the reference kernel")
        if num_layers < 0:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self._epsilon = epsilon
        self._dropout_rate = dropout_rate
        self._activation = activation
        self.layers = nn.LayerList()
        for _ in range(num_layers):
            self.layers.append(_FusedMTLayer(
                embed_dim, num_heads, dim_feedforward, dropout_rate,
                activation, epsilon))

    def forward(self, src, attn_mask=None, caches=None, time_step=None,
                **kw):
        h = src
        new_caches = []
        offset = int(time_step) if time_step is not None else \
            (caches[0][0].shape[1] if caches and caches[0][0] is not None
             else 0)
        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            h, c = layer(h, attn_mask, cache, offset)
            new_caches.append(c)
        if caches is not None:
            return h, new_caches
        return h


class _FusedMTLayer(nn.Layer):
    def __init__(self, d, nh, dff, p, act, eps):
        super().__init__()
        self.ln1 = nn.LayerNorm(d, epsilon=eps)
        self.qkv = nn.Linear(d, 3 * d)
        self.out_proj = nn.Linear(d, d)
        self.ln2 = nn.LayerNorm(d, epsilon=eps)
        self.ffn1 = nn.Linear(d, dff)
        self.ffn2 = nn.Linear(dff, d)
        self.nh = nh
        self.act = act

    def forward(self, x, attn_mask, cache, offset):
        from ...nn import functional as F
        B, S, D = x.shape
        hd = D // self.nh
        y = self.ln1(x)
        qkv = self.qkv(y).reshape([B, S, 3, self.nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if cache is not None:
            from ...ops.manipulation import concat
            if cache[0] is not None and cache[0].shape[1] > 0:
                k = concat([cache[0], k], axis=1)
                v = concat([cache[1], v], axis=1)
            new_cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None)
        out = self.out_proj(out.reshape([B, S, D]))
        h = x + out
        z = self.ln2(h)
        a = getattr(F, self.act)(self.ffn1(z))
        return h + self.ffn2(a), new_cache
