from . import models
