from . import moe
