"""Mixture-of-experts layer (expert parallelism).

Parity: python/paddle/incubate/distributed/models/moe/moe_layer.py
(reference — MoELayer :263 with global_scatter/global_gather all-to-all
dispatch :119,:167).

TPU-native: dense einsum dispatch/combine (GShard style) — tokens are
one-hot routed into per-expert buffers with capacity, experts run batched
(one big MXU matmul per expert weight), results combine weighted.  Under a
mesh with an "expert" (or "model") axis, sharding the expert dim of the
dispatched tensor makes XLA emit the all-to-all pair, replacing the
reference's NCCL global_scatter/global_gather.
"""
from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....core.dispatch import apply_op
from .....nn.layer_base import Layer
from .....nn.layers import LayerList
from .....ops._helpers import targ
from .gate import NaiveGate, GShardGate, SwitchGate


class MoELayer(Layer):
    """Parity: MoELayer (reference moe_layer.py:263).

    experts: list of Layers (applied per expert); gate: config dict or gate
    layer.  Input [B, S, D] or [N, D]; output same shape.
    """

    def __init__(self, d_model, experts: List[Layer], gate=None,
                 moe_group=None, mp_group=None, recompute_interval=0,
                 capacity_factor: float = 1.25, **kwargs):
        super().__init__()
        self.d_model = d_model
        self.experts = LayerList(experts)
        self.num_expert = len(experts)
        self.capacity_factor = capacity_factor
        if gate is None or isinstance(gate, dict):
            gtype = (gate or {}).get("type", "gshard")
            topk = (gate or {}).get("top_k", 2)
            cls = {"gshard": GShardGate, "switch": SwitchGate,
                   "naive": NaiveGate}[gtype]
            self.gate = cls(d_model, self.num_expert, topk=topk)
        else:
            self.gate = gate

    def forward(self, x):
        orig_shape = x.shape
        from .....ops.manipulation import reshape
        flat = reshape(x, [-1, self.d_model])
        n_tokens = flat.shape[0]
        capacity = max(1, int(self.capacity_factor * n_tokens /
                              self.num_expert) * self.gate.topk)

        combine_w, expert_idx, aux = self.gate(flat)
        self.l_aux = aux

        # one-hot dispatch with capacity (GShard dense routing)
        def dispatch(v, w, idx):
            k = idx.shape[1]
            oh = jax.nn.one_hot(idx, self.num_expert,
                                dtype=jnp.float32)      # [N,k,E]
            pos = jnp.cumsum(oh.reshape(-1, self.num_expert),
                             axis=0).reshape(v.shape[0], k,
                                             self.num_expert) - 1.0
            keep = pos < capacity
            oh = oh * keep
            pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                    dtype=jnp.float32)  # [N,k,E,C]
            disp = jnp.einsum("nke,nkec,nd->ecd", oh, pos_oh,
                              v.astype(jnp.float32))    # [E,C,D]
            comb = jnp.einsum("nk,nke,nkec->nec",
                              w.astype(jnp.float32), oh, pos_oh)
            return disp.astype(v.dtype), comb.astype(v.dtype)

        disp, comb = apply_op("moe_dispatch", dispatch,
                              (flat, combine_w, expert_idx))

        # per-expert forward on [C, D] buffers (batched MXU work)
        from .....ops.manipulation import unbind, stack
        exp_in = unbind(disp, axis=0)
        exp_out = [self.experts[e](exp_in[e])
                   for e in range(self.num_expert)]
        out_buf = stack(exp_out, axis=0)                # [E,C,D]

        def combine(buf, comb_w):
            return jnp.einsum("ecd,nec->nd", buf.astype(jnp.float32),
                              comb_w.astype(jnp.float32)).astype(buf.dtype)

        out = apply_op("moe_combine", combine, (out_buf, comb))
        return reshape(out, orig_shape)
