"""MoE gates.

Parity: python/paddle/incubate/distributed/models/moe/gate/ (reference —
GShard, Switch, naive gates).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....core.dispatch import apply_op
from .....nn.layer_base import Layer
from .....nn import initializer as I
from .....ops.moe_gate import topk_gate


def _gshard_aux(probs, top_i, num_expert):
    """mean_prob * fraction_routed per expert (GShard eq.), from the
    top-1 assignment."""
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(jax.nn.one_hot(top_i[:, 0], num_expert), axis=0)
    return jnp.sum(me * ce) * num_expert


class NaiveGate(Layer):
    """Top-k softmax gate (reference gate/naive_gate.py).

    All gates here route through ``ops.moe_gate.topk_gate`` — the same
    softmax/top-k used by the Mixtral block and the fused serving
    dispatch, so the implementations cannot drift apart."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__()
        self.num_expert = num_expert
        self.topk = topk
        self.weight = self.create_parameter(
            [d_model, num_expert], default_initializer=I.XavierUniform())

    def forward(self, x):
        """Returns (combine_weights [N, k], expert_idx [N, k], aux_loss)."""
        def fn(v, w):
            top_w, top_i, _ = topk_gate(v @ w, self.topk)
            return top_w.astype(v.dtype), top_i
        w, i = apply_op("naive_gate", fn, (x, self.weight))
        return w, i, None


class GShardGate(NaiveGate):
    """GShard top-2 gate with load-balancing aux loss (reference
    gate/gshard_gate.py)."""

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, topk)

    def forward(self, x):
        def fn(v, w):
            top_w, top_i, probs = topk_gate(v @ w, self.topk)
            aux = _gshard_aux(probs, top_i, self.num_expert)
            return top_w.astype(v.dtype), top_i, aux
        w, i, aux = apply_op("gshard_gate", fn, (x, self.weight))
        return w, i, aux


class SwitchGate(NaiveGate):
    """Switch (top-1) gate (reference gate/switch_gate.py).

    No renormalization: the combine weight is the raw routing
    probability of the selected expert."""

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        super().__init__(d_model, num_expert, world_size, 1)

    def forward(self, x):
        def fn(v, w):
            top_w, top_i, probs = topk_gate(v @ w, 1, renormalize=False)
            aux = _gshard_aux(probs, top_i, self.num_expert)
            return top_w.astype(v.dtype), top_i, aux
        w, i, aux = apply_op("switch_gate", fn, (x, self.weight))
        return w, i, aux
