from .moe_layer import MoELayer
from .gate import GShardGate, SwitchGate, NaiveGate
