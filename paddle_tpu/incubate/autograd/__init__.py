"""paddle.incubate.autograd (parity: python/paddle/incubate/autograd/
__all__ = [vjp, jvp, Jacobian, Hessian, enable_prim, disable_prim,
forward_grad, grad]).

TPU-native: the reference's "prim" lowering (decompose to primitive ops
for the static AD pass) is absorbed by jax/XLA — every op here is
already primitive-backed, so enable_prim/disable_prim toggle a flag the
translator does not need.  jvp is forward-over-reverse (two VJPs via
create_graph), the classical identity Jv = d/du [ (J^T u) . v ]."""
from __future__ import annotations

from typing import Sequence

from ...core.tensor import Tensor
from ...autograd import tape as _tape
from ...autograd.functional import jacobian as _jacobian, \
    hessian as _hessian

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

_PRIM = {"enabled": False}


def enable_prim():
    """Ops are already primitive-level under jax; the flag is kept for
    API parity and introspection."""
    _PRIM["enabled"] = True


def disable_prim():
    _PRIM["enabled"] = False


def prim_enabled():
    return _PRIM["enabled"]


def _tolist(xs):
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def vjp(func, xs, v=None):
    """Parity: incubate.autograd.vjp — returns (func(xs), vjp_result)."""
    xs_l = _tolist(xs)
    for x in xs_l:
        x.stop_gradient = False
    ys = func(*xs_l)
    ys_l = _tolist(ys)
    seeds = _tolist(v) if v is not None else None
    grads = _tape.grad(ys_l, xs_l, grad_outputs=seeds,
                       retain_graph=True, allow_unused=True)
    if not isinstance(grads, list):
        grads = [grads]
    out = grads if isinstance(xs, (list, tuple)) else grads[0]
    return ys, out


def _tangent(outs, ins, vs):
    """Forward-over-reverse core: tangents of ``outs`` at input
    tangents ``vs`` via two nested VJPs."""
    import jax.numpy as jnp
    us = []
    for y in outs:
        u = Tensor._from_value(jnp.zeros_like(y._value))
        u.stop_gradient = False
        us.append(u)
    s = None
    for y, u in zip(outs, us):
        term = (y * u).sum()
        s = term if s is None else s + term
    gx = _tape.grad([s], ins, create_graph=True, allow_unused=True)
    if not isinstance(gx, list):
        gx = [gx]
    t = None
    for g, vv in zip(gx, vs):
        if g is None:
            continue
        term = (g * vv).sum()
        t = term if t is None else t + term
    jv = _tape.grad([t], us, allow_unused=True)
    return jv if isinstance(jv, list) else [jv]


def jvp(func, xs, v=None):
    """Parity: incubate.autograd.jvp."""
    import jax.numpy as jnp
    xs_l = _tolist(xs)
    for x in xs_l:
        x.stop_gradient = False
    ys = func(*xs_l)
    ys_l = _tolist(ys)
    vs = _tolist(v) if v is not None else \
        [Tensor._from_value(jnp.ones_like(x._value)) for x in xs_l]
    jv = _tangent(ys_l, xs_l, vs)
    out = jv if isinstance(ys, (list, tuple)) else jv[0]
    return ys, out


class Jacobian:
    """Parity: incubate.autograd.Jacobian — row access over the full
    jacobian."""

    def __init__(self, func, xs, is_batched=False):
        self._jac = _jacobian(func, xs, create_graph=False)

    def __getitem__(self, idx):
        return self._jac[idx]

    @property
    def shape(self):
        return self._jac.shape


class Hessian:
    """Parity: incubate.autograd.Hessian."""

    def __init__(self, func, xs, is_batched=False):
        self._hes = _hessian(func, xs, create_graph=False)

    def __getitem__(self, idx):
        return self._hes[idx]

    @property
    def shape(self):
        return self._hes.shape


def forward_grad(outputs, inputs, grad_inputs=None):
    """Parity: incubate.autograd.forward_grad (prim-mode forward AD):
    tangents of ``outputs`` given input tangents."""
    import jax.numpy as jnp
    outs = _tolist(outputs)
    ins = _tolist(inputs)
    vs = _tolist(grad_inputs) if grad_inputs is not None else \
        [Tensor._from_value(jnp.ones_like(x._value)) for x in ins]
    jv = _tangent(outs, ins, vs)
    return jv if isinstance(outputs, (list, tuple)) else jv[0]


def grad(outputs, inputs, grad_outputs=None):
    """Parity: incubate.autograd.grad (the prim-mode reverse grad)."""
    return _tape.grad(_tolist(outputs), _tolist(inputs),
                      grad_outputs=grad_outputs, allow_unused=True)
