"""paddle_tpu.geometric — graph learning ops.

Capability parity with python/paddle/geometric/ (reference: message
passing send_u_recv/send_ue_recv/send_uv
python/paddle/geometric/message_passing/send_recv.py, segment ops
python/paddle/geometric/math.py over phi graph_send_recv /
segment_pool kernels).

TPU-native design: gathers + `jax.ops.segment_*` reductions, which XLA
lowers to sorted-scatter kernels — no CUDA atomics needed.  The segment
count (`num_segments` / out_size) must be static for jit; it defaults to
the eager value like the reference's infer-from-data path.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.dispatch import apply_op
from ..ops._helpers import as_value

_MESSAGE_OPS = None


def _message_op(name: str):
    global _MESSAGE_OPS
    if _MESSAGE_OPS is None:
        _MESSAGE_OPS = {"add": jnp.add, "sub": jnp.subtract,
                        "mul": jnp.multiply, "div": jnp.divide}
    try:
        return _MESSAGE_OPS[name]
    except KeyError:
        raise ValueError(
            f"message_op must be one of {sorted(_MESSAGE_OPS)}, got "
            f"{name!r}") from None

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _n_segments(ids_val, count) -> int:
    if count is not None:
        return int(count)
    if ids_val.size == 0:
        return 0
    return int(jnp.max(ids_val)) + 1


def _segment_reduce_values(x, ids, n, pool_type):
    """The one segment-reduction implementation (sum/mean/max/min over
    jax.ops.segment_*).  Empty segments produce 0 in every mode and
    every dtype — extrema fills are masked by a per-segment count, not
    isfinite (which is vacuously true for integer dtypes)."""
    if pool_type in ("sum", "add"):
        return jax.ops.segment_sum(x, ids, num_segments=n)
    count = jax.ops.segment_sum(jnp.ones((x.shape[0],), jnp.float32), ids,
                                num_segments=n)
    shape = (n,) + (1,) * (x.ndim - 1)
    count = count.reshape(shape)
    if pool_type == "mean":
        total = jax.ops.segment_sum(x, ids, num_segments=n)
        return (total / jnp.maximum(count, 1)).astype(total.dtype)
    pool = {"max": jax.ops.segment_max, "min": jax.ops.segment_min}[
        pool_type]
    out = pool(x, ids, num_segments=n)
    return jnp.where(count > 0, out, 0).astype(x.dtype)


def _segment(name, pool_type, data, segment_ids, num_segments=None):
    ids_val = as_value(segment_ids).astype(jnp.int32)
    n = _n_segments(ids_val, num_segments)

    def fn(x, ids):
        return _segment_reduce_values(x, ids, n, pool_type)

    return apply_op(name, fn, (data, ids_val))


def segment_sum(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_sum."""
    return _segment("segment_sum", "sum", data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_mean (empty segments → 0, like
    the reference's segment_pool MEAN)."""
    return _segment("segment_mean", "mean", data, segment_ids)


def segment_max(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_max (empty segments → 0)."""
    return _segment("segment_max", "max", data, segment_ids)


def segment_min(data, segment_ids, name=None):
    """Parity: paddle.geometric.segment_min (empty segments → 0)."""
    return _segment("segment_min", "min", data, segment_ids)


def _recv_reduce(name, messages, dst_val, pool_type, n):
    """Reduce edge messages into destination nodes."""

    def fn(msg, dst):
        return _segment_reduce_values(msg, dst, n, pool_type)

    return apply_op(name, fn, (messages, dst_val))


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges and reduce at
    destinations (parity: paddle.geometric.send_u_recv)."""
    src_val = as_value(src_index).astype(jnp.int32)
    dst_val = as_value(dst_index).astype(jnp.int32)
    n = _n_segments(dst_val, out_size) if out_size is not None \
        else as_value(x).shape[0]

    def gather(xv, src):
        return jnp.take(xv, src, axis=0)

    messages = apply_op("send_u", gather, (x, src_val))
    return _recv_reduce("send_u_recv", messages, dst_val, reduce_op, n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine gathered node features with edge features, then reduce
    (parity: paddle.geometric.send_ue_recv)."""
    src_val = as_value(src_index).astype(jnp.int32)
    dst_val = as_value(dst_index).astype(jnp.int32)
    n = _n_segments(dst_val, out_size) if out_size is not None \
        else as_value(x).shape[0]
    combine = _message_op(message_op)

    def fn_msg(xv, ev, src):
        return combine(jnp.take(xv, src, axis=0), ev)

    messages = apply_op("send_ue", fn_msg, (x, y, src_val))
    return _recv_reduce("send_ue_recv", messages, dst_val, reduce_op, n)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features
    (parity: paddle.geometric.send_uv)."""
    src_val = as_value(src_index).astype(jnp.int32)
    dst_val = as_value(dst_index).astype(jnp.int32)
    combine = _message_op(message_op)

    def fn(xv, yv, src, dst):
        return combine(jnp.take(xv, src, axis=0),
                       jnp.take(yv, dst, axis=0))

    return apply_op("send_uv", fn, (x, y, src_val, dst_val))


# ---------------------------------------------------------------------------
# GNN mini-batch sampling (parity: python/paddle/geometric/sampling/
# neighbors.py sample_neighbors:23 / weighted_sample_neighbors, and
# reindex.py reindex_graph:25 / reindex_heter_graph)
# ---------------------------------------------------------------------------
from ..ops.op_surface import (reindex_graph,               # noqa: E402
                              weighted_sample_neighbors)
from ..core.tensor import Tensor as _Tensor                # noqa: E402


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Parity: geometric.sample_neighbors — uniform sampling without
    replacement over a CSC graph; the TPU form is the same Gumbel
    top-k kernel as weighted_sample_neighbors with unit weights (fixed
    dense shapes, XLA-friendly)."""
    import jax.numpy as jnp
    rw = row._value if isinstance(row, _Tensor) else jnp.asarray(row)
    ones = _Tensor._from_value(
        jnp.ones(rw.reshape(-1).shape, jnp.float32))
    if return_eids:
        if eids is None:
            raise ValueError("return_eids=True requires eids")
        out, cnt = weighted_sample_neighbors(
            row, colptr, ones, input_nodes, sample_size=sample_size)
        # map sampled positions back to eids via the row-position table
        return out, cnt, _gather_eids(row, colptr, input_nodes, out,
                                      cnt, eids)
    return weighted_sample_neighbors(row, colptr, ones, input_nodes,
                                     sample_size=sample_size)


def _gather_eids(row, colptr, seeds, out, cnt, eids):
    import numpy as _np
    rw = _np.asarray(row._value if isinstance(row, _Tensor) else row) \
        .reshape(-1)
    cp = _np.asarray(colptr._value if isinstance(colptr, _Tensor)
                     else colptr).reshape(-1)
    sd = _np.asarray(seeds._value if isinstance(seeds, _Tensor)
                     else seeds).reshape(-1)
    ev = _np.asarray(eids._value if isinstance(eids, _Tensor)
                     else eids).reshape(-1)
    out_np = _np.asarray(out._value).reshape(len(sd), -1)
    cnt_np = _np.asarray(cnt._value).reshape(-1)
    res = []
    for i, s in enumerate(sd):
        lo, hi = int(cp[s]), int(cp[s + 1])
        nbr_eid = {}
        for pos in range(lo, hi):
            nbr_eid.setdefault(int(rw[pos]), int(ev[pos]))
        for v in out_np[i][: cnt_np[i]]:
            res.append(nbr_eid[int(v)])
    return _Tensor(_np.asarray(res, _np.int64))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Parity: geometric.reindex_heter_graph — reindex over multiple
    edge types: the hashtable (first-occurrence order over seeds then
    each type's neighbors) is shared, edges stay per-type concatenated."""
    import numpy as _np
    xv = _np.asarray(x._value if isinstance(x, _Tensor) else x) \
        .reshape(-1).astype(_np.int64)
    nbs = [_np.asarray(n._value if isinstance(n, _Tensor) else n)
           .reshape(-1).astype(_np.int64) for n in neighbors]
    cts = [_np.asarray(c._value if isinstance(c, _Tensor) else c)
           .reshape(-1).astype(_np.int64) for c in count]
    remap = {}
    out_nodes = []
    for v in xv:
        v = int(v)
        if v not in remap:
            remap[v] = len(out_nodes)
            out_nodes.append(v)
    srcs, dsts = [], []
    for nb, ct in zip(nbs, cts):
        for v in nb:
            v = int(v)
            if v not in remap:
                remap[v] = len(out_nodes)
                out_nodes.append(v)
        srcs.append(_np.asarray([remap[int(v)] for v in nb], _np.int64))
        dsts.append(_np.repeat(_np.arange(len(xv), dtype=_np.int64), ct))
    return (_Tensor(_np.concatenate(srcs)),
            _Tensor(_np.concatenate(dsts)),
            _Tensor(_np.asarray(out_nodes, _np.int64)))


__all__ += ["sample_neighbors", "weighted_sample_neighbors",
            "reindex_graph", "reindex_heter_graph"]
