"""Memory-efficient array redistribution between meshes (round 25).

"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075): moving a placed param+optimizer tree
from one mesh to another (dp=8 -> dp=4 after losing hosts, an fsdp x tp
reshape) must never stage the replicated array — per-chip peak memory
stays bounded by the LARGEST DESTINATION SHARD, not the full tensor,
and only the bytes whose owner actually changes move at all.

The module has three layers:

1. **Plan arithmetic** (pure host integers, no jax arrays): a shard
   layout is a ``{device: box}`` map (``box`` = per-dim ``(start,
   stop)``); :func:`plan_leaf` decomposes a destination layout against
   a source layout and counts, per destination device, the bytes
   already resident there (``adopted``) vs the bytes that must travel
   (``moved``).  The full-gather equivalent — what the checkpoint
   round trip / naive all-gather pays — is ``n_dst_devices x nbytes``.

2. **Apply** (:func:`redistribute_array` / :func:`redistribute_tree`):
   per leaf, each destination shard is either ADOPTED (the device
   already holds exactly that box: the existing single-device buffer is
   reused, zero copies — replicated params on surviving devices, or
   any leaf whose placement is unchanged) or ASSEMBLED from only the
   overlapping source shards into a dst-shard-sized host buffer and
   ``device_put`` to its one target chip.  The full array is never
   materialized anywhere: per-chip transient peak = the leaf's largest
   destination shard.

3. **Live reshape** (:func:`live_reshape`): re-place a
   :class:`~paddle_tpu.jit.train_step.TrainStep`'s params + optimizer
   state onto a new mesh IN PLACE (the optimizer's live state dicts
   keep their identity), then rebuild the step on the new mesh — its
   placement passes find every array already in its target sharding
   and adopt it.  This is what turns ``Engine.fit``'s r08 elastic
   restart into a live reshape instead of a checkpoint round trip
   (``Engine.request_reshape``).

Observability: ``redistribute_bytes_total{kind=moved|full_gather_equiv}``
records every apply — the ratio is the headline the r25 bench gates
(< 0.5x for dp halving).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["LeafPlan", "RedistributionPlan", "normalize_index",
           "plan_leaf", "redistribute_array", "redistribute_tree",
           "live_reshape"]

Box = Tuple[Tuple[int, int], ...]


# ---------------------------------------------------------------------------
# plan arithmetic (host-only; tier-1 tests drive these with plain dicts)
# ---------------------------------------------------------------------------
def normalize_index(index, shape) -> Box:
    """A jax ``devices_indices_map`` index (tuple of slices, possibly
    fewer than ndim, with None endpoints) as concrete per-dim
    ``(start, stop)`` pairs."""
    index = tuple(index)
    out = []
    for d, n in enumerate(shape):
        if d < len(index):
            s = index[d]
            start = 0 if s.start is None else int(s.start)
            stop = int(n) if s.stop is None else int(s.stop)
        else:
            start, stop = 0, int(n)
        out.append((start, stop))
    return tuple(out)


def box_nelems(box: Box) -> int:
    n = 1
    for start, stop in box:
        n *= max(0, stop - start)
    return n


def box_overlap(a: Box, b: Box) -> Optional[Box]:
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(a0, b0), min(a1, b1)
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


@dataclass
class LeafPlan:
    """Byte accounting for one array's move between two shard layouts.

    ``moved_bytes`` counts every destination-shard byte whose source
    lives on a DIFFERENT device (it crosses chips); ``adopted_bytes``
    the bytes each destination device already holds under the source
    layout.  ``full_gather_equiv_bytes`` is the naive-restore bill:
    every destination device materializes the full array.
    ``max_dst_shard_bytes`` bounds the per-chip transient peak of the
    apply — the largest STAGING buffer any single chip allocates
    (adopted shards reuse their existing device buffer and stage
    nothing, so a replicated leaf that only drops devices peaks at
    zero)."""
    key: str
    shape: Tuple[int, ...]
    itemsize: int
    nbytes: int
    n_dst_devices: int
    moved_bytes: int
    adopted_bytes: int
    full_gather_equiv_bytes: int
    max_dst_shard_bytes: int

    @property
    def unchanged(self) -> bool:
        return self.moved_bytes == 0


def plan_leaf(key: str, shape, itemsize: int,
              src_map: Dict[Any, Box], dst_map: Dict[Any, Box]
              ) -> LeafPlan:
    """Decompose ``dst_map`` against ``src_map`` (device keys only need
    to be hashable and comparable across the two maps)."""
    shape = tuple(int(s) for s in shape)
    itemsize = int(itemsize)
    nbytes = int(np.prod(shape, dtype=np.int64)) * itemsize if shape \
        else itemsize
    moved = adopted = 0
    max_dst = 0
    for dev, box in dst_map.items():
        want = box_nelems(box) * itemsize
        local_box = src_map.get(dev)
        local = 0
        if local_box is not None:
            ov = box_overlap(box, local_box)
            if ov is not None:
                local = box_nelems(ov) * itemsize
        if local_box != box:
            # assembly path: one dst-shard-sized staging buffer; the
            # adopt path (placement unchanged on this device) reuses
            # the existing buffer and stages nothing
            max_dst = max(max_dst, want)
        adopted += local
        moved += want - local
    return LeafPlan(key=key, shape=shape, itemsize=itemsize,
                    nbytes=nbytes, n_dst_devices=len(dst_map),
                    moved_bytes=moved, adopted_bytes=adopted,
                    full_gather_equiv_bytes=len(dst_map) * nbytes,
                    max_dst_shard_bytes=max_dst)


@dataclass
class RedistributionPlan:
    """Tree-level rollup of :class:`LeafPlan` accounting."""
    leaves: List[LeafPlan] = field(default_factory=list)

    def add(self, leaf: LeafPlan) -> None:
        self.leaves.append(leaf)

    @property
    def moved_bytes(self) -> int:
        return sum(p.moved_bytes for p in self.leaves)

    @property
    def adopted_bytes(self) -> int:
        return sum(p.adopted_bytes for p in self.leaves)

    @property
    def full_gather_equiv_bytes(self) -> int:
        return sum(p.full_gather_equiv_bytes for p in self.leaves)

    @property
    def per_chip_peak_bytes(self) -> int:
        """Largest buffer any one chip stages: leaves move one at a
        time, so the transient peak is the max single destination
        shard, never a full tensor."""
        return max((p.max_dst_shard_bytes for p in self.leaves),
                   default=0)

    @property
    def full_gather_peak_bytes(self) -> int:
        """What the naive path peaks at per chip: at least one full
        leaf replica resident while it reshards."""
        return max((p.nbytes for p in self.leaves), default=0)

    def summary(self) -> Dict[str, Any]:
        fg = self.full_gather_equiv_bytes
        return {
            "leaves": len(self.leaves),
            "moved_bytes": self.moved_bytes,
            "adopted_bytes": self.adopted_bytes,
            "full_gather_equiv_bytes": fg,
            "moved_over_full_gather": (self.moved_bytes / fg) if fg
            else 0.0,
            "per_chip_peak_bytes": self.per_chip_peak_bytes,
            "full_gather_peak_bytes": self.full_gather_peak_bytes,
        }


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------
def _norm_map(sharding, shape) -> Dict[Any, Box]:
    return {d: normalize_index(idx, shape)
            for d, idx in sharding.devices_indices_map(
                tuple(shape)).items()}


def redistribute_array(arr, dst_sharding, key: str = "array"):
    """Move one jax array to ``dst_sharding`` shard-by-shard; returns
    ``(new_array, LeafPlan)``.  Destination shards whose device already
    holds exactly that box reuse the existing device buffer; the rest
    are assembled host-side from only the overlapping source shards
    (one dst-shard-sized staging buffer at a time — the replicated
    array never exists)."""
    import jax

    shape = tuple(arr.shape)
    src_map = _norm_map(arr.sharding, shape)
    dst_map = _norm_map(dst_sharding, shape)
    plan = plan_leaf(key, shape, arr.dtype.itemsize, src_map, dst_map)
    if arr.sharding == dst_sharding:
        return arr, plan
    shards = {s.device: s.data for s in arr.addressable_shards}
    pieces = []
    for dev, box in dst_map.items():
        src_box = src_map.get(dev)
        if src_box == box and dev in shards:
            pieces.append(shards[dev])          # adopt: zero copies
            continue
        # distinct source boxes only (replication repeats a box across
        # devices — copy each region once, preferring the local holder)
        distinct: Dict[Box, Any] = {}
        for sdev, sbox in src_map.items():
            if sdev not in shards:
                continue
            if sbox not in distinct or sdev == dev:
                distinct[sbox] = sdev
        out = np.empty([hi - lo for lo, hi in box],
                       dtype=np.dtype(arr.dtype))
        for sbox, sdev in distinct.items():
            ov = box_overlap(box, sbox)
            if ov is None:
                continue
            dst_sl = tuple(slice(o0 - b0, o1 - b0) for (o0, o1), (b0, _)
                           in zip(ov, box))
            src_sl = tuple(slice(o0 - s0, o1 - s0) for (o0, o1), (s0, _)
                           in zip(ov, sbox))
            out[dst_sl] = np.asarray(shards[sdev])[src_sl]
        pieces.append(jax.device_put(out, dev))
    new = jax.make_array_from_single_device_arrays(
        shape, dst_sharding, pieces)
    return new, plan


_METRIC = None


def _bytes_counter(registry=None):
    global _METRIC
    from ..observability import default_registry
    r = registry if registry is not None else default_registry()
    c = r.counter(
        "redistribute_bytes_total",
        "array-redistribution traffic per live mesh reshape, by kind: "
        "'moved' = bytes whose owning chip changed (the only bytes "
        "that cross chips), 'full_gather_equiv' = what the checkpoint "
        "round trip / naive all-gather restore would have staged "
        "(n_dst_chips x full array) — the r25 bench gates the ratio",
        labels=("kind",))
    if registry is None:
        _METRIC = c
    return c


def redistribute_tree(arrays: Dict[str, Any],
                      shardings: Dict[str, Any],
                      registry=None, publish: bool = True):
    """Redistribute a flat ``{key: jax.Array}`` tree onto per-key
    target shardings, one leaf at a time.  Returns ``(new_tree,
    RedistributionPlan)`` and (by default) publishes the byte counts
    to ``redistribute_bytes_total``."""
    plan = RedistributionPlan()
    out = {}
    for k, v in arrays.items():
        new, leaf = redistribute_array(v, shardings[k], key=k)
        plan.add(leaf)
        out[k] = new
    if publish:
        c = _bytes_counter(registry)
        c.labels(kind="moved").inc(plan.moved_bytes)
        c.labels(kind="full_gather_equiv").inc(
            plan.full_gather_equiv_bytes)
    return out, plan


# ---------------------------------------------------------------------------
# live TrainStep reshape
# ---------------------------------------------------------------------------
def _target_shardings(step, jmesh, axis=None):
    """The new mesh's placements for every param and optimizer-state
    leaf, computed with the SAME helpers TrainStep's setup uses — the
    rebuilt step then finds every array already placed and adopts it."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .spmd import SpecLayout, llama_param_specs, spec_axes

    repl = NamedSharding(jmesh, PartitionSpec())
    sd = step.model.state_dict()
    param_sh: Dict[str, Any] = {k: repl
                                for k in step._trainable + step._frozen}
    leaf_sh: Dict[str, Dict[str, Any]] = {}
    mode = getattr(step, "_mode", "1d")
    if mode == "2d":
        sizes = dict(jmesh.shape)
        tp_live = sizes.get("tp", 1) > 1
        layout = SpecLayout(tp_axis="tp" if tp_live else None,
                            fsdp_axis="fsdp")
        shapes = {k: tuple(sd[k]._value.shape) for k in step._trainable}
        specs = llama_param_specs(step._trainable, layout,
                                  shapes=shapes, mesh=jmesh)
        for k in step._trainable:
            ok = step._shardable.get(k, False) and \
                bool(spec_axes(specs[k]))
            sh = NamedSharding(jmesh, specs[k]) if ok else repl
            param_sh[k] = sh
            pshape = shapes[k]
            leaf_sh[k] = {
                name: (sh if ok and hasattr(v, "shape")
                       and tuple(v.shape) == pshape else repl)
                for name, v in step._opt_states[k].items()
                if hasattr(v, "shape")}
    else:
        if axis is None:
            axis = step._axis
        deg = jmesh.shape[axis]
        row = NamedSharding(jmesh, PartitionSpec(axis))
        for k in step._trainable:
            pshape = tuple(sd[k]._value.shape)
            ok = (step._shardable.get(k, False) and len(pshape) >= 1
                  and pshape[0] % deg == 0)
            leaf_sh[k] = {
                name: (row if ok and hasattr(v, "shape")
                       and tuple(v.shape) == pshape else repl)
                for name, v in step._opt_states[k].items()
                if hasattr(v, "shape")}
    return param_sh, leaf_sh, repl


def live_reshape(step, mesh, registry=None):
    """Re-place ``step``'s params + optimizer state onto ``mesh``
    device-to-device (no checkpoint, no replicated staging copy) and
    rebuild the TrainStep there.  Returns ``(new_step, plan)``.

    The optimizer's live state dicts keep their identity — leaves are
    swapped in place — so the rebuilt step's ``_refresh_state`` finds
    each one already carrying its target sharding and adopts it (the
    same equality probe that makes its steady state transfer-free).
    The old step's compiled executable is dropped; the first step on
    the new mesh re-traces (a compile, not a data move)."""
    from ..distributed.process_mesh import as_jax_mesh
    from .spmd import resolve_mesh_axis
    from .train_step import ShardingConfig, TrainStep

    if not getattr(step, "_sharded", False):
        raise ValueError(
            "live_reshape needs a sharded TrainStep (a replicated step "
            "has no placement to move — just rebuild it)")
    cfg = getattr(step, "_shard_cfg", None) or ShardingConfig()
    mode = getattr(step, "_mode", "1d")
    if mode == "2d":
        jmesh = as_jax_mesh(mesh)
        if "fsdp" not in jmesh.axis_names:
            raise ValueError(
                "reshaping a 2D (fsdp x tp) TrainStep needs a mesh "
                "with an 'fsdp' axis; got %r"
                % (tuple(jmesh.axis_names),))
        new_axis = None
    else:
        jmesh, new_axis, deg = resolve_mesh_axis(
            mesh, cfg.axis, -1, candidates=("dp", "sharding", "data"))
        if deg <= 1:
            raise ValueError(
                "live_reshape target mesh is degenerate (axis size 1); "
                "rebuild a replicated TrainStep instead")
    param_sh, leaf_sh, repl = _target_shardings(step, jmesh, new_axis)

    sd = step.model.state_dict()
    tree: Dict[str, Any] = {}
    shmap: Dict[str, Any] = {}
    for k in step._trainable + step._frozen:
        tree[f"model.{k}"] = sd[k]._value
        shmap[f"model.{k}"] = param_sh.get(k, repl)
    for k in step._trainable:
        for name, v in step._opt_states[k].items():
            if hasattr(v, "shape"):
                tree[f"opt.{k}.{name}"] = v
                shmap[f"opt.{k}.{name}"] = leaf_sh[k][name]
    new_tree, plan = redistribute_tree(tree, shmap, registry=registry)
    for k in step._trainable + step._frozen:
        sd[k]._value = new_tree[f"model.{k}"]
    for k in step._trainable:
        st = step._opt_states[k]          # optimizer._state's own dict
        for name in list(st.keys()):
            moved = new_tree.get(f"opt.{k}.{name}")
            if moved is not None:
                st[name] = moved
    cfg2 = cfg if mode == "2d" else ShardingConfig(
        stage=cfg.stage, degree=-1, axis=cfg.axis,
        bucket_mb=cfg.bucket_mb, loss_reduction=cfg.loss_reduction)
    new_step = TrainStep(step.model, step.criterion, step.optimizer,
                         clip_norm=step.clip_norm, mesh=jmesh,
                         sharding=cfg2)
    return new_step, plan
