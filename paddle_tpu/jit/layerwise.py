"""Layer-wise backward with the optimizer update fused into the reverse
sweep — the max-resident single-chip training form.

Why: a fused ``TrainStep`` materializes ALL parameter gradients before
the update (params + grads resident together), capping one 16 GB chip at
~3B bf16 params.  Here the backward is an explicit reverse ``lax.scan``
over the layer stack: each layer's gradients exist only inside its scan
iteration, are consumed immediately by the optimizer rule, and the
updated layer slice is written back into the (donated) stacked parameter
buffers.  Peak memory is params + ONE layer's grads + the per-layer
activation checkpoints, so ~5.4B params train on a single v5e.

This is the TPU-native analog of the reference's sharding stage-3
per-layer gather/release machinery
(python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage3.py:85) — where the reference streams param shards
around NCCL, a single chip streams GRADIENT LIVENESS through the
schedule instead.

Mechanics (one jit, donated buffers):
  1. forward ``lax.scan`` over stacked block params, saving each layer's
     INPUT (the activation checkpoint, [L, B, S, H] bf16);
  2. head loss (fp32 log-softmax xent) under ``jax.checkpoint`` so the
     [B, S, V] logits are recomputed in backward, not stored;
  3. reverse ``lax.scan``: re-run layer l from its checkpoint under
     ``jax.vjp``, get (dparams_l, dx), apply the Adafactor update rule
     to the layer slice right there, emit updated params/state;
  4. embedding/head/final-norm update from their direct grads.

Adafactor is the default rule (factored second moments, O(rows+cols)
state — the T5/PaLM recipe); any Optimizer whose ``_update_rule`` is
pure jnp works.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..models.llama import LlamaConfig, param_count
from ..ops.pallas_kernels import _flash_rope_sdpa, rope_tables

__all__ = ["LlamaLayerwiseTrainStep"]

# stacked-buffer leaf -> LlamaForCausalLM parameter name (one source of
# truth for from_model/state_dict/set_state_dict)
_KEY_MAP = {
    "wq": "llama.layers.{}.self_attn.q_proj.weight",
    "wk": "llama.layers.{}.self_attn.k_proj.weight",
    "wv": "llama.layers.{}.self_attn.v_proj.weight",
    "wo": "llama.layers.{}.self_attn.o_proj.weight",
    "gate": "llama.layers.{}.mlp.gate_proj.weight",
    "up": "llama.layers.{}.mlp.up_proj.weight",
    "down": "llama.layers.{}.mlp.down_proj.weight",
    "ln1": "llama.layers.{}.input_layernorm.weight",
    "ln2": "llama.layers.{}.post_attention_layernorm.weight",
}


def _rms_norm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)).astype(x.dtype) * w


def _block_fn(p, h, cos, sin, cfg: LlamaConfig):
    """One decoder block over the per-layer param dict ``p``."""
    B, S, H = h.shape
    nh = cfg.num_attention_heads
    kv = cfg.num_key_value_heads
    dh = cfg.hidden_size // nh

    x = _rms_norm(h, p["ln1"], cfg.rms_norm_eps)
    q = (x @ p["wq"]).reshape(B, S, nh, dh)
    k = (x @ p["wk"]).reshape(B, S, kv, dh)
    v = (x @ p["wv"]).reshape(B, S, kv, dh)
    if kv != nh:
        k = jnp.repeat(k, nh // kv, axis=2)
        v = jnp.repeat(v, nh // kv, axis=2)
    # heads-first for the fused rope+flash kernel
    out = _flash_rope_sdpa(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                           jnp.swapaxes(v, 1, 2), cos, sin, True)
    out = jnp.swapaxes(out, 1, 2).reshape(B, S, nh * dh)
    h = h + out @ p["wo"]

    x = _rms_norm(h, p["ln2"], cfg.rms_norm_eps)
    gate = x @ p["gate"]
    up = x @ p["up"]
    return h + (jax.nn.silu(gate) * up) @ p["down"]


def _head_loss(hL, norm_w, head_w, labels, cfg: LlamaConfig,
               chunk: int = 2048):
    """Shift-by-one LM loss, fp32 log-softmax (same convention as
    LlamaPretrainingCriterion: labels roll left, last position ignored).

    Streamed over token chunks under per-chunk remat so the fp32 logits
    never materialize at [B*S, V] — forward AND backward peak at one
    [chunk, V] block (the jax-native form of the framework's streaming
    softmax-xent custom VJP in nn/functional/loss.py)."""
    B, S, H = hL.shape
    x = _rms_norm(hL, norm_w, cfg.rms_norm_eps).reshape(B * S, H)
    shift = jnp.concatenate(
        [labels[:, 1:], jnp.full((B, 1), -100, labels.dtype)],
        axis=1).reshape(B * S)
    n_tok = B * S
    pad = (-n_tok) % chunk
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, H), x.dtype)])
        shift = jnp.concatenate(
            [shift, jnp.full((pad,), -100, shift.dtype)])
    xc = x.reshape(-1, chunk, H)
    lc = shift.reshape(-1, chunk)

    def chunk_fn(carry, xl):
        xk, lk = xl
        logits = (xk @ head_w).astype(jnp.float32)     # (chunk, V)
        valid = lk != -100
        tgt = jnp.where(valid, lk, 0).astype(jnp.int32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tok = jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0]
        nll = (lse - tok) * valid
        return (carry[0] + nll.sum(),
                carry[1] + valid.sum().astype(jnp.float32)), None

    (s, c), _ = lax.scan(jax.checkpoint(chunk_fn),
                         (jnp.zeros((), jnp.float32),
                          jnp.zeros((), jnp.float32)), (xc, lc))
    return s / jnp.maximum(c, 1.0)


class LlamaLayerwiseTrainStep:
    """Single-chip max-resident Llama pretraining step (see module doc).

    Parameters live OUTSIDE any Layer as stacked device arrays; use
    :meth:`init` for a fresh model or :meth:`from_model` to adopt the
    weights of an existing ``LlamaForCausalLM`` (parity tests)."""

    def __init__(self, cfg: LlamaConfig, optimizer=None):
        from ..optimizer.optimizer import Adafactor
        self.cfg = cfg
        self.opt = optimizer if optimizer is not None else \
            Adafactor(1e-3, parameters=[])
        self.params: Optional[Dict[str, Any]] = None
        self.opt_state: Optional[Dict[str, Any]] = None
        self._step_fn = None
        self._dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" \
            else jnp.float32

    # -- parameter construction ---------------------------------------------
    def _shapes(self):
        c = self.cfg
        h, i, v = c.hidden_size, c.intermediate_size, c.vocab_size
        nh = c.num_attention_heads
        dh = h // nh
        kvd = c.num_key_value_heads * dh
        L = c.num_hidden_layers
        blocks = {
            "wq": (L, h, nh * dh), "wk": (L, h, kvd), "wv": (L, h, kvd),
            "wo": (L, nh * dh, h), "gate": (L, h, i), "up": (L, h, i),
            "down": (L, i, h), "ln1": (L, h), "ln2": (L, h),
        }
        return {"emb": (v, h), "norm": (h,), "head": (h, v),
                "blocks": blocks}

    def init(self, seed: int = 0):
        """Device-side init (no host copy of the full model)."""
        cfg = self.cfg
        std = cfg.initializer_range
        shapes = self._shapes()
        dt = self._dtype

        def build(key):
            ks = jax.random.split(key, 3 + len(shapes["blocks"]))
            p = {
                "emb": jax.random.normal(ks[0], shapes["emb"], dt) * std,
                "norm": jnp.ones(shapes["norm"], dt),
                "head": jax.random.normal(ks[1], shapes["head"], dt) * std,
                "blocks": {},
            }
            for j, (name, shp) in enumerate(
                    sorted(shapes["blocks"].items())):
                if name.startswith("ln"):
                    p["blocks"][name] = jnp.ones(shp, dt)
                else:
                    p["blocks"][name] = jax.random.normal(
                        ks[3 + j], shp, dt) * std
            return p

        self.params = jax.jit(build)(jax.random.PRNGKey(seed))
        self.opt_state = self._init_opt_state()
        return self

    def from_model(self, model):
        """Adopt weights from a LlamaForCausalLM (same math, stacked)."""
        L = self.cfg.num_hidden_layers
        sd = {k: v._value for k, v in model.state_dict().items()}

        def stack(fmt):
            return jnp.stack([sd[fmt.format(l)] for l in range(L)])

        # copies: the adopted model's own steps may DONATE its buffers
        self.params = {
            "emb": jnp.array(sd["llama.embed_tokens.weight"]),
            "norm": jnp.array(sd["llama.norm.weight"]),
            "head": jnp.array(sd["lm_head.weight"]),
            "blocks": {name: stack(fmt)
                       for name, fmt in _KEY_MAP.items()},
        }
        self.opt_state = self._init_opt_state()
        return self

    def state_dict(self):
        """Checkpoint in LlamaForCausalLM's key layout (per-layer slices
        of the stacked buffers), so a layerwise-trained model loads
        straight into the standard eager model for serving — and vice
        versa.  The unstacked leaves are COPIED: the step donates
        self.params, so aliasing views would die at the next step."""
        from ..core.tensor import Tensor
        if self.params is None:
            raise RuntimeError("no parameters: call init()/from_model()")
        out = {
            "llama.embed_tokens.weight": Tensor._from_value(
                jnp.array(self.params["emb"])),
            "llama.norm.weight": Tensor._from_value(
                jnp.array(self.params["norm"])),
            "lm_head.weight": Tensor._from_value(
                jnp.array(self.params["head"])),
        }
        for name, stacked in self.params["blocks"].items():
            for l in range(self.cfg.num_hidden_layers):
                out[_KEY_MAP[name].format(l)] = Tensor._from_value(
                    stacked[l])
        return out

    def set_state_dict(self, state):
        """Load a LlamaForCausalLM-layout state dict into the stacked
        buffers (inverse of state_dict).  Optimizer state is re-
        initialized — like from_model — since moment statistics
        accumulated for the previous weights do not apply to the loaded
        ones (restoring mid-run optimizer state is the distributed-
        checkpoint API's job, which saves it explicitly)."""
        def val(k):
            v = state[k]
            return getattr(v, "_value", v)

        L = self.cfg.num_hidden_layers
        self.params = {
            "emb": jnp.asarray(val("llama.embed_tokens.weight"),
                               self._dtype),
            "norm": jnp.asarray(val("llama.norm.weight"), self._dtype),
            "head": jnp.asarray(val("lm_head.weight"), self._dtype),
            "blocks": {
                name: jnp.stack(
                    [jnp.asarray(val(fmt.format(l)), self._dtype)
                     for l in range(L)])
                for name, fmt in _KEY_MAP.items()
            },
        }
        self.opt_state = self._init_opt_state()
        return self

    def _init_opt_state(self):
        """Optimizer state per leaf; block-param states stacked over L
        (sliced per layer inside the reverse scan)."""
        opt = self.opt

        def leaf_state(shape):
            class _P:
                _value = jnp.zeros(shape, self._dtype)
            return opt._init_state(_P())

        def stacked_state(shape):
            L, per = shape[0], shape[1:]
            st = leaf_state(per)
            return {k: jnp.broadcast_to(v, (L,) + v.shape).copy()
                    for k, v in st.items()}

        shapes = self._shapes()
        return {
            "emb": leaf_state(shapes["emb"]),
            "norm": leaf_state(shapes["norm"]),
            "head": leaf_state(shapes["head"]),
            "blocks": {k: stacked_state(s)
                       for k, s in shapes["blocks"].items()},
        }

    # -- the fused step ------------------------------------------------------
    def _build(self):
        cfg = self.cfg
        opt = self.opt
        L = cfg.num_hidden_layers

        def step(params, opt_state, lr, ids, labels):
            hyper = {"lr": lr}
            S = ids.shape[1]
            dh = cfg.hidden_size // cfg.num_attention_heads
            cos, sin = rope_tables(S, dh, cfg.rope_theta)

            h0 = params["emb"][ids]

            # 1. forward scan, saving each layer's input (checkpoint)
            def fwd(h, p_l):
                return _block_fn(p_l, h, cos, sin, cfg), h

            hL, xs = lax.scan(fwd, h0, params["blocks"])

            # 2. head loss (chunk-streamed fp32 softmax, see _head_loss)
            head = lambda hl, nw, hw: _head_loss(hl, nw, hw, labels, cfg)
            loss, head_vjp = jax.vjp(head, hL, params["norm"],
                                     params["head"])
            dhL, dnorm, dhead = head_vjp(jnp.ones((), jnp.float32))

            # 3. reverse sweep: per-layer vjp + optimizer update written
            # back into the SAME loop-carried buffers (dynamic-update-
            # slice on a while-loop carry stays in place under XLA; a
            # scan emitting ys would allocate a second full param set)
            tree_map = jax.tree_util.tree_map

            def bwd(i, carry):
                dh, blocks, bstate = carry
                l = L - 1 - i
                take = lambda a: lax.dynamic_index_in_dim(
                    a, l, 0, keepdims=False)
                p_l = tree_map(take, blocks)
                st_l = tree_map(take, bstate)
                x_l = take(xs)
                _, vjp = jax.vjp(
                    lambda p, x: _block_fn(p, x, cos, sin, cfg), p_l, x_l)
                dp, dx = vjp(dh)
                new_p, new_st = {}, {}
                for k in p_l:
                    new_p[k], new_st[k] = opt._update_rule(
                        p_l[k], dp[k], st_l[k], hyper)
                put = lambda a, nv: lax.dynamic_update_index_in_dim(
                    a, nv, l, 0)
                blocks = tree_map(put, blocks, new_p)
                bstate = tree_map(put, bstate, new_st)
                return (dx, blocks, bstate)

            dh0, new_blocks, new_bstate = lax.fori_loop(
                0, L, bwd, (dhL, params["blocks"], opt_state["blocks"]))

            # 4. embedding + head-side updates from direct grads
            demb = jnp.zeros(params["emb"].shape, jnp.float32) \
                .at[ids].add(dh0.astype(jnp.float32))
            demb = demb.astype(params["emb"].dtype)
            new_params = {"blocks": new_blocks}
            new_state = {"blocks": new_bstate}
            for name, g in (("emb", demb), ("norm", dnorm),
                            ("head", dhead)):
                new_params[name], new_state[name] = opt._update_rule(
                    params[name], g, opt_state[name], hyper)
            return loss, new_params, new_state

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))

    def __call__(self, ids, labels):
        from ..core.tensor import Tensor
        if self.params is None:
            raise RuntimeError("call .init() or .from_model() first")
        if self._step_fn is None:
            self._build()
        ids_v = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
        lab_v = labels._value if isinstance(labels, Tensor) \
            else jnp.asarray(labels)
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        loss, self.params, self.opt_state = self._step_fn(
            self.params, self.opt_state, lr, ids_v, lab_v)
        return Tensor._from_value(loss)

    def param_count(self) -> int:
        return param_count(self.cfg)

