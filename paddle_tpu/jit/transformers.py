"""Dy2static AST transformers.

Parity: python/paddle/jit/dy2static/transformers/ (reference — the 18 AST
transformers driven by program_translator.py:776; ifelse_transformer.py,
loop_transformer.py, logical_transformer.py, call_transformer.py).

TPU-native design: the rewritten constructs target the jax structured
control-flow primitives through runtime converters (convert_ops.py) — a
tensor-predicate ``if`` becomes ``lax.cond``, a tensor ``while`` becomes
``lax.while_loop`` — so data-dependent control flow lives INSIDE the
compiled XLA module instead of breaking the trace.  Python-value
predicates keep exact python semantics (the converters dispatch at run
time, like the reference's convert_* operators).

Supported subset (documented, mirrors the reference's practical coverage):
- ``if``/``elif``/``else`` with tensor predicates, where branches assign
  variables (no ``return``/``break`` inside a transformed branch);
- ``while`` with tensor predicates (no ``break``/``continue``); NOTE:
  a traced-tensor ``while`` compiles to ``lax.while_loop``, which XLA
  cannot reverse-differentiate — use it in inference/metrics paths, or a
  python-bounded ``for`` (stays unrolled, fully differentiable) in
  training code;
- ``for i in range(...)``: python bounds stay a plain unrolled python
  loop (differentiable); traced-tensor bounds lower to a while loop
  (forward-only, same XLA constraint);
- ``and`` / ``or`` / ``not`` over tensor operands (short-circuiting
  preserved for python values);
- recursive conversion of called user functions (convert_call).
Constructs outside the subset are left as plain python: they still work
whenever their predicates are python values, exactly like before.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types
from typing import List, Optional, Set

_COUNTER = [0]


def _fresh(prefix: str) -> str:
    _COUNTER[0] += 1
    return f"__pt_{prefix}_{_COUNTER[0]}"


# ---------------------------------------------------------------------------
# name analysis
# ---------------------------------------------------------------------------
class _Names(ast.NodeVisitor):
    def __init__(self):
        self.stored: Set[str] = set()
        self.loaded: Set[str] = set()
        self.funcs: Set[str] = set()   # nested defs: not data-flow values

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            self.stored.add(node.id)
        else:
            self.loaded.add(node.id)

    def visit_FunctionDef(self, node):   # don't descend into nested defs
        self.funcs.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass


def _analyze(stmts) -> _Names:
    v = _Names()
    for s in stmts:
        v.visit(s)
    return v


def _contains(stmts, kinds) -> bool:
    class F(ast.NodeVisitor):
        found = False

        def generic_visit(self, node):
            if isinstance(node, kinds):
                self.found = True
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                super().generic_visit(node)
    f = F()
    for s in stmts:
        f.visit(s)
    return f.found


def _try_read_default(name: str) -> ast.expr:
    """``_jst.try_read(lambda: name)`` — evaluated at def time, yields the
    current outer binding or the UNDEF sentinel."""
    return ast.Call(
        func=ast.Attribute(ast.Name("_jst", ast.Load()), "try_read",
                           ast.Load()),
        args=[ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=ast.Name(name, ast.Load()))],
        keywords=[])


def _names_tuple(names: List[str], ctx) -> ast.expr:
    return ast.Tuple([ast.Name(n, ctx()) for n in names], ctx())


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _fndef(name, args, body):
    fd = ast.FunctionDef(name=name, args=args, body=body,
                         decorator_list=[])
    fd.type_params = []   # required field on py3.12 ASTs
    return fd

class Dy2StaticTransformer(ast.NodeTransformer):
    # -- logical ops --------------------------------------------------------
    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fname = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        out = node.values[-1]
        for val in reversed(node.values[:-1]):
            out = ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()), fname,
                                   ast.Load()),
                args=[ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=val),
                    ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=out)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_logical_not", ast.Load()),
                args=[node.operand], keywords=[])
        return node

    # -- if/else ------------------------------------------------------------
    def visit_If(self, node: ast.If):
        self.generic_visit(node)
        branches = node.body + node.orelse
        if _contains(branches, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
            return node   # unsupported in a branch fn: keep python

    # assigned names (either branch) become the branch-fn outputs
        t = _analyze(node.body)
        f = _analyze(node.orelse)
        assigned = sorted((t.stored | f.stored) - t.funcs - f.funcs
                          - {"_", "_jst"})
        if not assigned:
            return node   # side-effect-only branches: keep python

        tname, fname = _fresh("true_fn"), _fresh("false_fn")
        args = ast.arguments(
            posonlyargs=[], kwonlyargs=[], kw_defaults=[],
            args=[ast.arg(n) for n in assigned],
            defaults=[_try_read_default(n) for n in assigned])
        ret = ast.Return(_names_tuple(assigned, ast.Load))
        true_def = _fndef(tname, args, node.body + [ret])
        false_def = _fndef(fname, args,
                           (node.orelse or [ast.Pass()]) + [ret])
        call = ast.Assign(
            targets=[_names_tuple(assigned, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_ifelse", ast.Load()),
                args=[node.test, ast.Name(tname, ast.Load()),
                      ast.Name(fname, ast.Load())],
                keywords=[]))
        return [true_def, false_def, call]

    # -- while --------------------------------------------------------------
    def visit_While(self, node: ast.While):
        self.generic_visit(node)
        if node.orelse or _contains(
                node.body, (ast.Break, ast.Continue, ast.Return,
                            ast.Yield, ast.YieldFrom)):
            return node

        body_names = _analyze(node.body)
        # anything the body stores may be read by the condition or after
        # the loop (unknowable locally) — carry all stored names
        loop_vars = sorted(body_names.stored - body_names.funcs
                           - {"_", "_jst"})
        if not loop_vars:
            return node

        cname, bname = _fresh("while_cond"), _fresh("while_body")
        args = ast.arguments(posonlyargs=[], kwonlyargs=[],
                             kw_defaults=[], defaults=[],
                             args=[ast.arg(n) for n in loop_vars])
        cond_def = _fndef(cname, args, [ast.Return(node.test)])
        body_def = _fndef(
            bname, args,
            node.body + [ast.Return(_names_tuple(loop_vars, ast.Load))])
        call = ast.Assign(
            targets=[_names_tuple(loop_vars, ast.Store)],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_while_loop", ast.Load()),
                args=[ast.Name(cname, ast.Load()),
                      ast.Name(bname, ast.Load()),
                      ast.Tuple([_try_read_default(n)
                                 for n in loop_vars], ast.Load())],
                keywords=[]))
        return [cond_def, body_def, call]

    # -- for i in range(...) ------------------------------------------------
    def visit_For(self, node: ast.For):
        self.generic_visit(node)
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or _contains(node.body, (ast.Break, ast.Continue,
                                         ast.Return, ast.Yield,
                                         ast.YieldFrom))):
            return node

        rargs = node.iter.args
        if len(rargs) == 1:
            start, stop, step = ast.Constant(0), rargs[0], ast.Constant(1)
        elif len(rargs) == 2:
            start, stop, step = rargs[0], rargs[1], ast.Constant(1)
        else:
            start, stop, step = rargs

        ivar = node.target.id
        body_names = _analyze(node.body)
        loop_vars = sorted(body_names.stored - body_names.funcs
                           - {ivar, "_", "_jst"})

        bname = _fresh("for_body")
        args = ast.arguments(
            posonlyargs=[], kwonlyargs=[], kw_defaults=[], defaults=[],
            args=[ast.arg(ivar)] + [ast.arg(n) for n in loop_vars])
        body_def = _fndef(
            bname, args,
            node.body + [ast.Return(_names_tuple(loop_vars, ast.Load))])
        # the index stays bound after the loop (python semantics)
        targets = _names_tuple([ivar] + loop_vars, ast.Store)
        call = ast.Assign(
            targets=[targets],
            value=ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_for_range", ast.Load()),
                args=[start, stop, step, ast.Name(bname, ast.Load()),
                      ast.Tuple([_try_read_default(n)
                                 for n in loop_vars], ast.Load())],
                keywords=[]))
        return [body_def, call]

    # -- nested calls -------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        # only wrap plain-name calls: attribute calls are overwhelmingly
        # framework/methods, and wrapping them would be pure overhead
        if isinstance(node.func, ast.Name) and node.func.id not in (
                "range", "len", "print", "isinstance", "super", "_jst"):
            node.func = ast.Call(
                func=ast.Attribute(ast.Name("_jst", ast.Load()),
                                   "convert_call", ast.Load()),
                args=[node.func], keywords=[])
        return node


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def convert_function(fn):
    """AST-convert a python function for tracing; returns the original on
    any failure (no-source builtins, exotic constructs)."""
    from . import convert_ops as _jst_mod

    if isinstance(fn, functools.partial):
        inner = convert_function(fn.func)
        return functools.partial(inner, *fn.args, **(fn.keywords or {}))

    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn

    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []   # strip @to_static etc.

    new_tree = Dy2StaticTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(fn.__globals__)
    glb["_jst"] = _jst_mod
    # rebind closure freevars as globals (values snapshotted now)
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError:
                pass

    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    ns: dict = {}
    exec(code, glb, ns)
    out = ns[fdef.name]
    out = functools.wraps(fn)(out)
    out.__pt_converted__ = True
    return out


def convert_to_static(call):
    """Entry used by StaticFunction: convert a function or bound method."""
    if isinstance(call, types.MethodType):
        conv = convert_function(call.__func__)
        if conv is call.__func__:
            return call
        return types.MethodType(conv, call.__self__)
    conv = convert_function(call)
    return conv
